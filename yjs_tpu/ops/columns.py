"""Host-side columnar (struct-of-arrays) transcoding for the TPU batch engine.

This module replaces the reference's pointer-graph decode/integrate prelude
(reference src/utils/encoding.js:127-198, src/structs/Item.js:354-397) with a
columnar pipeline:

  wire update bytes
    -> ``ItemRef`` records (flat decode, no Doc required)
    -> causal schedule  (the dependency-stack integrator of
       encoding.js:225-321, recast as a per-client queue fixpoint)
    -> pre-split pass   (all run splits computed *before* device integration,
       mirroring what Snapshot.splitSnapshotAffectedStructs does for
       snapshots — reference src/utils/Snapshot.js:141-154 — so the device
       item table is static)
    -> ``StepPlan``     (padded int32 columns ready for the JAX kernel)

The :class:`DocMirror` is the host twin of one document's struct store: it owns
the immutable per-row columns (client, clock, length, origin, rightOrigin) and
the variable-length payloads (content objects live host-side only; device
memory holds fixed-width columns, per SURVEY.md §7 core data layout).  The
device owns the *dynamic* integration state: linked-list links, list head,
deleted bits.

Pre-splitting is sound because YATA placement of a run is determined
element-wise by (origin, rightOrigin, client) — integrating the fragments of a
run (each fragment's origin = last id of its left sibling fragment, rightOrigin
inherited, exactly the splitItem rule of reference src/structs/Item.js:84-120)
yields the same total order as integrating the whole run and splitting later.
"""

from __future__ import annotations

import bisect
import os
from dataclasses import dataclass, field

import numpy as np

from ..coding import UpdateDecoderV1, UpdateDecoderV2
from ..core import read_item_content
from ..lib0 import decoding
from ..lib0.binary import BIT6, BIT7, BIT8, BITS5
from ..lib0.decoding import Decoder
from ..native import SRC_DELETED, SRC_FRAMED, SRC_NONE, SRC_SPILL, SRC_UTF8
from . import plan_cache as _pc

NULL = -1  # null id / null row sentinel in every int column
# sched8 sentinels (shared with the level kernel, yjs_tpu/ops/kernels.py)
NO_LEFT_WRITE = -3  # chain member: placed by its predecessor's succ write
GATHER_SUCC = -2  # succ: the old successor of `check` (== right when fast)


# ---------------------------------------------------------------------------
# Flat decode: wire bytes -> ItemRef records (no Doc involved)
# ---------------------------------------------------------------------------


class LazyContent:
    """Content payload referenced by byte range, decoded only on demand.

    The native transcoder (yjs_tpu/native) emits byte offsets instead of
    decoding payloads; most rows are never materialized (state vectors,
    diffs, integration itself need no payload bytes).  ``end`` is the
    exclusive end of the V1-framed payload bytes: the native wire encoder
    copies [ofs, end) verbatim when re-emitting unsplit rows."""

    __slots__ = ("buf", "ofs", "end", "ref")

    def __init__(self, buf: bytes, ofs: int, ref: int, end: int = -1):
        self.buf = buf
        self.ofs = ofs
        self.end = end
        self.ref = ref

    def realize(self):
        decoder = Decoder(self.buf)
        decoder.pos = self.ofs
        return read_item_content(UpdateDecoderV1(decoder), self.ref)


class _TypeNameShim:
    """Minimal decoder stand-in for type_refs constructors (only XmlElement
    and XmlHook read anything: the node/hook name string)."""

    __slots__ = ("_name",)

    def __init__(self, name: str | None):
        self._name = name

    def read_string(self) -> str:
        return self._name

    read_key = read_string


class LazyContentV2:
    """V2 content payload as byte ranges into the update's stream regions
    (UTF-8 string arena / self-delimiting rest-stream values), decoded on
    demand — the V2 twin of :class:`LazyContent` (reference
    UpdateDecoder.js:270-293 stream layout)."""

    __slots__ = ("buf", "kind", "ofs", "end", "ofs2", "end2", "count")

    def __init__(self, buf, kind, ofs, end, ofs2, end2, count):
        self.buf = buf
        self.kind = kind
        self.ofs = ofs
        self.end = end
        self.ofs2 = ofs2
        self.end2 = end2
        self.count = count

    def _any_at(self, ofs: int):
        d = Decoder(self.buf)
        d.pos = ofs
        return decoding.read_any(d)

    def realize(self):
        from ..core import (
            ContentBinary,
            ContentEmbed,
            ContentFormat,
            ContentString,
            ContentType,
            type_refs,
        )
        from ..lib0.u16 import utf8_decode_u16

        k = self.kind
        if k == 4:
            return ContentString(utf8_decode_u16(self.buf[self.ofs : self.end]))
        if k == 8:
            from ..core import ContentAny

            d = Decoder(self.buf)
            d.pos = self.ofs
            return ContentAny([decoding.read_any(d) for _ in range(self.count)])
        if k == 6:
            return ContentFormat(
                utf8_decode_u16(self.buf[self.ofs : self.end]),
                self._any_at(self.ofs2),
            )
        if k == 5:
            return ContentEmbed(self._any_at(self.ofs))
        if k == 3:
            d = Decoder(self.buf)
            d.pos = self.ofs
            return ContentBinary(decoding.read_var_uint8_array(d))
        if k == 7:
            name = (
                utf8_decode_u16(self.buf[self.ofs : self.end])
                if self.ofs >= 0
                else None
            )
            return ContentType(type_refs[self.count](_TypeNameShim(name)))
        raise ValueError(f"unexpected lazy v2 content kind {k}")


@dataclass(slots=True)
class ItemRef:
    """A decoded, not-yet-integrated struct (Item or GC) as plain data."""

    client: int
    clock: int
    length: int
    origin: tuple[int, int] | None = None  # (client, clock)
    right_origin: tuple[int, int] | None = None
    parent_name: str | None = None  # root-type key
    parent_id: tuple[int, int] | None = None  # nested type-item parent id
    parent_sub: str | None = None
    content: object | None = None  # AbstractContent | LazyContent; None = GC
    content_ref: int = 0  # wire content-ref (0 = GC struct)
    is_gc: bool = False

    def materialize(self):
        if isinstance(self.content, (LazyContent, LazyContentV2)):
            self.content = self.content.realize()
        return self.content

    def split(self, offset: int) -> "ItemRef":
        """Split off and return the right part at ``offset`` elements
        (reference src/structs/Item.js:84-120 field rules)."""
        right_content = self.materialize().splice(offset)
        right = ItemRef(
            client=self.client,
            clock=self.clock + offset,
            length=self.length - offset,
            origin=(self.client, self.clock + offset - 1),
            right_origin=self.right_origin,
            parent_name=self.parent_name,
            parent_id=self.parent_id,
            parent_sub=self.parent_sub,
            content=right_content,
            content_ref=self.content_ref,
        )
        self.length = offset
        return right

    def trim_left(self, offset: int) -> None:
        """Drop the first ``offset`` already-known elements (the dedup
        `offset` path of reference src/structs/Item.js:745-755 and
        GC.js integrate)."""
        if self.content is not None:
            self.content = self.materialize().splice(offset)
        self.clock += offset
        self.length -= offset
        if not self.is_gc:
            self.origin = (self.client, self.clock - 1)


def decode_update_refs(update: bytes, v2: bool):
    """Decode an update into (refs_per_client, delete_ranges) without a Doc.

    Mirrors reference src/utils/encoding.js:127-198 (struct section) and
    src/utils/DeleteSet.js:270-285 (DS section header/ranges), but resolves
    nothing — root parents stay names, origins stay IDs.  V1 updates take
    the native columnar scanner when available (payloads stay lazy).
    """
    from ..native import NativeDecodeError

    try:
        if v2:
            return _decode_update_refs_native_v2(update)
        return _decode_update_refs_native(update)
    except NativeDecodeError:
        pass  # no toolchain / malformed input / legacy payload kinds: the
        # pure-Python decoder decides whether the bytes are really malformed
    decoder = Decoder(update)
    yd = UpdateDecoderV2(decoder) if v2 else UpdateDecoderV1(decoder)
    refs: dict[int, list[ItemRef]] = {}
    num_of_state_updates = decoding.read_var_uint(yd.rest_decoder)
    for _ in range(num_of_state_updates):
        number_of_structs = decoding.read_var_uint(yd.rest_decoder)
        client = yd.read_client()
        clock = decoding.read_var_uint(yd.rest_decoder)
        out = refs.setdefault(client, [])
        for _ in range(number_of_structs):
            info = yd.read_info()
            if (BITS5 & info) != 0:
                cant_copy_parent_info = (info & (BIT7 | BIT8)) == 0
                origin = yd.read_left_id() if (info & BIT8) == BIT8 else None
                right_origin = yd.read_right_id() if (info & BIT7) == BIT7 else None
                parent_name = None
                parent_id = None
                if cant_copy_parent_info:
                    if yd.read_parent_info():
                        parent_name = yd.read_string()
                    else:
                        pid = yd.read_left_id()
                        parent_id = (pid.client, pid.clock)
                parent_sub = (
                    yd.read_string()
                    if cant_copy_parent_info and (info & BIT6) == BIT6
                    else None
                )
                content = read_item_content(yd, info)
                ref = ItemRef(
                    client=client,
                    clock=clock,
                    length=content.get_length(),
                    origin=None if origin is None else (origin.client, origin.clock),
                    right_origin=None
                    if right_origin is None
                    else (right_origin.client, right_origin.clock),
                    parent_name=parent_name,
                    parent_id=parent_id,
                    parent_sub=parent_sub,
                    content=content,
                    content_ref=info & BITS5,
                )
                out.append(ref)
                clock += ref.length
            else:
                ln = yd.read_len()
                out.append(ItemRef(client=client, clock=clock, length=ln, is_gc=True))
                clock += ln

    # DS section (reference DeleteSet.js:270-285): (client, clock, len) ranges
    ds: list[tuple[int, int, int]] = []
    num_clients = decoding.read_var_uint(yd.rest_decoder)
    for _ in range(num_clients):
        yd.reset_ds_cur_val()
        client = decoding.read_var_uint(yd.rest_decoder)
        num_deletes = decoding.read_var_uint(yd.rest_decoder)
        for _ in range(num_deletes):
            ds.append((client, yd.read_ds_clock(), yd.read_ds_len()))
    return refs, ds


def _decode_update_refs_native(update: bytes):
    """Build ItemRefs from the native scanner's columns (V1 only)."""
    from ..lib0.u16 import utf8_decode_u16
    from ..native import decode_v1_columns

    cols, ds_cols = decode_v1_columns(update)
    refs: dict[int, list[ItemRef]] = {}
    n = len(cols["client"])
    # tolist() once: plain-int indexing is ~10x cheaper than boxing a numpy
    # scalar per field in the row loop
    client_a = cols["client"].tolist()
    clock_a = cols["clock"].tolist()
    length_a = cols["length"].tolist()
    oc, ok = cols["origin_client"].tolist(), cols["origin_clock"].tolist()
    rc, rk = cols["right_client"].tolist(), cols["right_clock"].tolist()
    info_a = cols["info"].tolist()
    pno, pnl = cols["parent_name_ofs"].tolist(), cols["parent_name_len"].tolist()
    pic, pik = cols["parent_id_client"].tolist(), cols["parent_id_clock"].tolist()
    pso, psl = cols["parent_sub_ofs"].tolist(), cols["parent_sub_len"].tolist()
    c_ofs = cols["content_ofs"].tolist()
    c_end = cols["content_end"].tolist()
    for i in range(n):
        client = client_a[i]
        ref_kind = info_a[i] & BITS5
        if ref_kind == 0:
            ref = ItemRef(
                client=client, clock=clock_a[i], length=length_a[i],
                is_gc=True,
            )
        else:
            ref = ItemRef(
                client=client,
                clock=clock_a[i],
                length=length_a[i],
                origin=None if oc[i] < 0 else (oc[i], ok[i]),
                right_origin=None if rc[i] < 0 else (rc[i], rk[i]),
                parent_name=None
                if pno[i] < 0
                else utf8_decode_u16(update[pno[i] : pno[i] + pnl[i]]),
                parent_id=None if pic[i] < 0 else (pic[i], pik[i]),
                parent_sub=None
                if pso[i] < 0
                else utf8_decode_u16(update[pso[i] : pso[i] + psl[i]]),
                content=LazyContent(update, c_ofs[i], info_a[i], c_end[i]),
                content_ref=ref_kind,
            )
        refs.setdefault(client, []).append(ref)
    ds = list(
        zip(
            ds_cols["client"].tolist(),
            ds_cols["clock"].tolist(),
            ds_cols["len"].tolist(),
        )
    )
    return refs, ds


def _decode_update_refs_native_v2(update: bytes):
    """Build ItemRefs from the native V2 scanner's columns."""
    from ..core import ContentDeleted
    from ..lib0.u16 import utf8_decode_u16
    from ..native import decode_v2_columns

    cols, ds_cols = decode_v2_columns(update)
    refs: dict[int, list[ItemRef]] = {}
    n = len(cols["client"])
    client_a = cols["client"].tolist()
    clock_a = cols["clock"].tolist()
    length_a = cols["length"].tolist()
    oc, ok = cols["origin_client"].tolist(), cols["origin_clock"].tolist()
    rc, rk = cols["right_client"].tolist(), cols["right_clock"].tolist()
    info_a = cols["info"].tolist()
    pno, pnl = cols["parent_name_ofs"].tolist(), cols["parent_name_len"].tolist()
    pic, pik = cols["parent_id_client"].tolist(), cols["parent_id_clock"].tolist()
    pso, psl = cols["parent_sub_ofs"].tolist(), cols["parent_sub_len"].tolist()
    c_ofs = cols["content_ofs"].tolist()
    c_end = cols["content_end"].tolist()
    c_ofs2 = cols["content_ofs2"].tolist()
    c_end2 = cols["content_end2"].tolist()
    c_cnt = cols["content_count"].tolist()
    for i in range(n):
        client = client_a[i]
        ref_kind = info_a[i] & BITS5
        if ref_kind == 0:
            ref = ItemRef(
                client=client, clock=clock_a[i], length=length_a[i],
                is_gc=True,
            )
        else:
            if ref_kind == 1:
                content = ContentDeleted(length_a[i])
            else:
                content = LazyContentV2(
                    update, ref_kind, c_ofs[i], c_end[i],
                    c_ofs2[i], c_end2[i], c_cnt[i],
                )
            ref = ItemRef(
                client=client,
                clock=clock_a[i],
                length=length_a[i],
                origin=None if oc[i] < 0 else (oc[i], ok[i]),
                right_origin=None if rc[i] < 0 else (rc[i], rk[i]),
                parent_name=None
                if pno[i] < 0
                else utf8_decode_u16(update[pno[i] : pno[i] + pnl[i]]),
                parent_id=None if pic[i] < 0 else (pic[i], pik[i]),
                parent_sub=None
                if pso[i] < 0
                else utf8_decode_u16(update[pso[i] : pso[i] + psl[i]]),
                content=content,
                content_ref=ref_kind,
            )
        refs.setdefault(client, []).append(ref)
    ds = list(
        zip(
            ds_cols["client"].tolist(),
            ds_cols["clock"].tolist(),
            ds_cols["len"].tolist(),
        )
    )
    return refs, ds


class UnsupportedUpdate(Exception):
    """The update uses features outside the device path's scope (nested
    types, map entries, subdocuments); the owning doc must fall back to the
    CPU reference core (the Provider gating of BASELINE.json's north star)."""


# ---------------------------------------------------------------------------
# StepPlan: what one flush hands to the device kernel for one doc
# ---------------------------------------------------------------------------


class _PlanCtx:
    """Opaque phase A -> phase B carrier for the split cold plan
    (ISSUE 15): the engine holds these while the segment planner
    co-plans a whole chunk of cold docs in one batched kernel call."""

    __slots__ = ("plan", "frag_sched", "applicable", "queries", "sd")


@dataclass
class StepPlan:
    """Per-doc inputs for one device integration step (un-padded)."""

    n_rows: int  # total rows in the mirror after this step
    # splits of already-integrated rows: (orig_row, new_row), ordered so that
    # multiple cuts of one original row appear right-to-left
    splits: list[tuple[int, int]] = field(default_factory=list)
    # integration schedule: (row, left_row, right_row, seg) in causal order
    sched: list[tuple[int, int, int, int]] = field(default_factory=list)
    # rows to mark deleted after integration
    delete_rows: list[int] = field(default_factory=list)
    # delete ranges applied this step (client, clock, len) — the DS section
    # of the step's emitted incremental update
    applied_ds: list[tuple[int, int, int]] = field(default_factory=list)
    # 8-field bulk schedule (row, left, right, check, succ, seg, fb_left,
    # fb_right) with dependency levels (1-based): see assign_levels
    sched8: list[tuple[int, int, int, int, int, int, int, int]] = field(
        default_factory=list
    )
    levels: list[int] = field(default_factory=list)
    n_levels: int = 0
    max_width: int = 0  # widest level (engine pack bucket sizing)
    # bulk-apply form (the default device path): FINAL right-link values of
    # every row whose link changed this step, plus segment-head updates —
    # the host planner resolves YATA placement against its own list state,
    # so the device applies one conflict-free scatter (the sort/rank-style
    # layout; the YATA scan kernels remain as the levels/seq paths)
    link_rows: list[int] = field(default_factory=list)
    link_vals: list[int] = field(default_factory=list)
    head_segs: list[int] = field(default_factory=list)
    head_vals: list[int] = field(default_factory=list)
    # structs placed by the segment-sorted conflict-free fast path
    # instead of the sequential YATA walk (ISSUE 9 accounting)
    fastpath_structs: int = 0

    def assign_levels(self, client_of_row) -> None:
        """Rewrite the causal schedule into the level-parallel bulk form.

        Items sharing a splice gap (same resolved left & right in the same
        segment) necessarily share (origin, rightOrigin) — post-split, a
        left row determines the origin id and vice versa — so YATA orders
        them by ascending client (reference Item.js case 1, :447-455).  The
        host pre-links each such group into a chain spliced in ONE bulk
        write; remaining items get one entry each.

        Chains also extend ACROSS groups: when a group's gap-left is the
        current tail of an already-emitted chain and its right matches the
        chain's right (sequential typing: each new run's origin is the last
        id of the previous run), the group joins that chain at the SAME
        level — the whole typing session splices in one bulk write instead
        of one level per run.  This flattens the reference's inherently
        sequential insertion chains (Item.js fast path :432-434) into O(1)
        levels for the common editing texture.

        Each sched8 entry is (row, left, right, check, succ, seg, fb_left,
        fb_right):
        - fast iff rl[check] == right (check==NULL: head test
          starts[seg]==right); all members of one chain share (check,
          right), so a chain is fast or deferred as a whole
        - splice: rl[left] = row (left>=0), starts[seg] = row (left==NULL),
          rl[row] = succ, where succ==GATHER_SUCC means the gathered old
          successor of `check`
        - on fast-check failure the item integrates sequentially with
          (row, fb_left, fb_right, seg) — its ORIGINAL YATA gap, which for
          stitched groups differs from the chain-head's (check, right).
        """
        groups: dict[tuple[int, int, int], list[int]] = {}
        order: list[tuple[int, int, int]] = []
        for i, (row, left, right, seg) in enumerate(self.sched):
            key = (left, right, seg)
            g = groups.get(key)
            if g is None:
                groups[key] = [i]
                order.append(key)
            else:
                g.append(i)

        self.sched8 = []
        self.levels = []
        lev_of_row: dict[int, int] = {}
        used: set[tuple[int, object]] = set()
        # chain tails open for stitching: tail row -> (entry idx, head
        # check, head right, level)
        tails: dict[int, tuple[int, int, int, int]] = {}
        n_levels = 0
        for key in order:
            left, right, seg = key
            idxs = groups[key]
            members = [self.sched[i][0] for i in idxs]
            if len(members) > 1:
                members.sort(key=client_of_row)
            t = tails.get(left) if left != NULL else None
            if t is not None and t[2] == right and self.sched8[t[0]][5] == seg:
                # stitch: continue the chain ending at `left` in place
                idx0, hchk, hr0, lev = t
                e = self.sched8[idx0]
                self.sched8[idx0] = e[:4] + (members[0],) + e[5:]
                for j, row in enumerate(members):
                    succ = (
                        members[j + 1] if j + 1 < len(members) else GATHER_SUCC
                    )
                    self.sched8.append(
                        (row, NO_LEFT_WRITE, hr0, hchk, succ, seg, left, right)
                    )
                    self.levels.append(lev)
                    lev_of_row[row] = lev
                del tails[left]
                tails[members[-1]] = (len(self.sched8) - 1, hchk, hr0, lev)
                # n_levels already covers lev: the head chain raised it
                continue
            base = 1 + max(lev_of_row.get(left, 0), lev_of_row.get(right, 0))
            # write-target key: rl[left] for real lefts, the segment's head
            # slot otherwise (distinct segments' head writes may share a
            # level — they scatter to distinct starts[] cells)
            gap: object = left if left != NULL else ("h", seg)
            lev = base
            while (lev, gap) in used:
                lev += 1
            used.add((lev, gap))
            for j, row in enumerate(members):
                entry_left = left if j == 0 else NO_LEFT_WRITE
                succ = members[j + 1] if j + 1 < len(members) else GATHER_SUCC
                self.sched8.append(
                    (row, entry_left, right, left, succ, seg, left, right)
                )
                self.levels.append(lev)
                lev_of_row[row] = lev
            tails[members[-1]] = (len(self.sched8) - 1, left, right, lev)
            n_levels = max(n_levels, lev)
        self.n_levels = n_levels
        width = [0] * n_levels
        for lev in self.levels:
            width[lev - 1] += 1
        self.max_width = max(width, default=0)

    def packed_levels(self):
        """The 8-field schedule grouped level-major ([L, W, 8] device pack)."""
        out: list[list[tuple[int, ...]]] = [[] for _ in range(self.n_levels)]
        for entry, lev in zip(self.sched8, self.levels):
            out[lev - 1].append(entry)
        return out


# ---------------------------------------------------------------------------
# DocMirror: host twin of one document
# ---------------------------------------------------------------------------


class DocMirror:
    """Host columnar mirror of one doc: immutable struct columns + payloads.

    Row indices are stable forever (append-only; splits append the right
    fragment as a new row).  The per-client fragment index maps (client,
    clock) -> row for origin/rightOrigin resolution, the columnar analogue of
    StructStore.find (reference src/utils/StructStore.js:123-177).

    Every (root type, map key) pair is a *segment*: an independent linked
    list on device.  Segment ``(name, None)`` is the root list of a
    YText/YArray/Xml root; ``(name, sub)`` is one YMap key's entry chain
    (reference AbstractType _start vs _map, src/types/AbstractType.js:255-
    288).  The same YATA kernel integrates both; the LWW rule for map
    chains (reference Item.js:497-507 tail-delete + :512-516 mid-chain
    self-delete, whose net effect is order-independent: every chain entry
    except the final tail is deleted) is applied host-side because the
    host replicates chain order anyway for exports.
    """

    def __init__(self, root_name: str = "text"):
        self.root_name = root_name
        # client <-> dense slot mapping
        self.client_of_slot: list[int] = []
        self.slot_of_client: dict[int, int] = {}
        # segment registry: (root name or None, parent_sub or None,
        # parent type-item row or NULL) -> seg id.  Root segments carry the
        # share-map name; NESTED segments (shared types inside ContentType
        # items, reference ContentType.js) are keyed by the row holding the
        # type item — the same YATA kernel integrates either kind
        self.segments: dict[tuple[str | None, str | None, int], int] = {}
        self.seg_info: list[tuple[str | None, str | None, int]] = []
        # rows fully deleted as known host-side (delete resolution + LWW);
        # type rows are length-1 so this is exact for the parent checks
        self._host_deleted_rows: set[int] = set()
        # per-map-segment host chain: rows in YATA order (tiny lists — one
        # entry per concurrent writer of one key)
        self.map_chain: dict[int, list[int]] = {}
        # host linked lists: the mirror of the device right_link/starts
        # state, maintained by the planner's own YATA resolution so each
        # flush ships final link values (StepPlan.link_*)
        self.list_next: list[int] = []  # per row; NULL = tail/unlinked
        self.head_of_seg: list[int] = []  # per seg; NULL = empty
        # reverse indexes for the recursive type-delete rule
        self._segs_of_parent: dict[int, list[int]] = {}
        self._rows_of_seg: dict[int, list[int]] = {}
        # rows already LWW-deleted (dedup for DS bookkeeping)
        self._lww_deleted: set[int] = set()
        # per-row columns (python lists; converted to numpy at flush)
        self.row_slot: list[int] = []
        self.row_clock: list[int] = []
        self.row_len: list[int] = []
        self.row_origin_slot: list[int] = []
        self.row_origin_clock: list[int] = []
        self.row_right_slot: list[int] = []
        self.row_right_clock: list[int] = []
        self.row_is_gc: list[bool] = []
        self.row_countable: list[bool] = []
        self.row_content: list[object | None] = []
        self.row_content_ref: list[int] = []
        self.row_seg: list[int] = []  # segment id (NULL for GC rows)
        # per-row content source for the native wire encoder (kind codes
        # from yjs_tpu.native: NONE/DELETED/FRAMED/UTF8/SPILL), precomputed
        # at row creation so encode never inspects content objects
        self.row_src_kind: list[int] = []
        self.row_src_buf: list[int] = []
        self.row_src_ofs: list[int] = []
        self.row_src_end: list[int] = []
        # source-buffer registry backing row_src_buf
        self._bufs: list[bytes] = []
        self._buf_ids: dict[int, int] = {}
        # persistent interned segment-name strings blob (UTF-8)
        self._strings = bytearray()
        self._interned: dict[str, tuple[int, int]] = {}
        # per-seg interned name/sub offsets, aligned with seg_info
        self.seg_name_ofs: list[int] = []
        self.seg_name_len: list[int] = []
        self.seg_sub_ofs: list[int] = []
        self.seg_sub_len: list[int] = []
        # numpy-view cache of the row columns, invalidated on any mutation
        self._gen = 0
        self._np_gen = -1
        self._np: dict[str, np.ndarray] = {}
        # merged delete-set arrays cache (grouped for the native encoder)
        self._ds_gen = 0
        self._ds_np_gen = -1
        self._ds_np: tuple | None = None
        # per-slot fragment index, sorted by clock
        self.frag_clock: list[list[int]] = []
        self.frag_row: list[list[int]] = []
        # per-slot state (next expected clock)
        self.state: list[int] = []
        # causally-early refs parked until their deps arrive
        # (reference StructStore pendingClientsStructRefs, StructStore.js:25-35)
        self.pending: dict[int, list[ItemRef]] = {}
        # delete ranges beyond known state (reference DeleteSet.js:317-322)
        self.pending_ds: list[tuple[int, int, int]] = []
        # applied delete ranges per slot (host bookkeeping for sync/export)
        self.ds: dict[int, list[tuple[int, int]]] = {}
        # updates queued since the last flush
        self._incoming: list[tuple[bytes, bool]] = []
        # plan-cache digest chain (ISSUE 9): advances on every successful
        # prepare / deterministic compact, poisons on anything else
        self.plan_frontier = _pc.seed_frontier(root_name)

    # -- client slots -------------------------------------------------------

    def slot(self, client: int) -> int:
        s = self.slot_of_client.get(client)
        if s is None:
            s = len(self.client_of_slot)
            self.slot_of_client[client] = s
            self.client_of_slot.append(client)
            self.frag_clock.append([])
            self.frag_row.append([])
            self.state.append(0)
        return s

    def get_state(self, client: int) -> int:
        s = self.slot_of_client.get(client)
        return 0 if s is None else self.state[s]

    @property
    def n_rows(self) -> int:
        return len(self.row_slot)

    def host_nbytes(self) -> int:
        """Rough host bytes this mirror holds (warm-tier accounting,
        ISSUE 7): retained update payloads + interned strings + the
        packed row/segment columns (~14 int-ish lists per row)."""
        return (
            sum(len(b) for b in self._bufs)
            + len(self._strings)
            + self.n_rows * 8 * 14
            + self.n_segs * 8 * 6
        )

    def deleted_ratio(self) -> float:
        """Deleted content length / total inserted length — the tier GC
        trigger (ISSUE 7).  Computed from the host delete-range
        bookkeeping; no device traffic."""
        total = sum(self.state)
        if not total:
            return 0.0
        deleted = sum(
            ln
            for ranges in self.ds.values()
            for _clock, ln in self._union_ranges(ranges)
        )
        return min(1.0, deleted / total)

    # -- segments -----------------------------------------------------------

    def _intern(self, s: str) -> tuple[int, int]:
        r = self._interned.get(s)
        if r is None:
            from ..lib0.u16 import u16_encode_utf8

            b = u16_encode_utf8(s)
            r = (len(self._strings), len(b))
            self._interned[s] = r
            self._strings.extend(b)
        return r

    def _buf_idx(self, b) -> int:
        k = id(b)
        j = self._buf_ids.get(k)
        if j is None:
            j = len(self._bufs)
            self._buf_ids[k] = j
            self._bufs.append(b)
        return j

    def seg(
        self, name: str | None, sub: str | None = None, parent_row: int = NULL
    ) -> int:
        key = (name, sub, parent_row)
        s = self.segments.get(key)
        if s is None:
            s = len(self.seg_info)
            self.segments[key] = s
            self.seg_info.append(key)
            self.head_of_seg.append(NULL)
            if parent_row != NULL:
                self._segs_of_parent.setdefault(parent_row, []).append(s)
            if name is None:
                self.seg_name_ofs.append(NULL)
                self.seg_name_len.append(0)
            else:
                no, nl = self._intern(name)
                self.seg_name_ofs.append(no)
                self.seg_name_len.append(nl)
            if sub is None:
                self.seg_sub_ofs.append(NULL)
                self.seg_sub_len.append(0)
            else:
                so, sl = self._intern(sub)
                self.seg_sub_ofs.append(so)
                self.seg_sub_len.append(sl)
        return s

    @property
    def n_segs(self) -> int:
        return len(self.seg_info)

    def seg_is_map(self, seg: int) -> bool:
        return self.seg_info[seg][1] is not None

    # -- row / fragment bookkeeping ----------------------------------------

    def _add_row(self, slot, clock, length, origin, right_origin, is_gc, content,
                 content_ref=0, seg=NULL):
        row = len(self.row_slot)
        self.row_slot.append(slot)
        self.row_clock.append(clock)
        self.row_len.append(length)
        if origin is None:
            self.row_origin_slot.append(NULL)
            self.row_origin_clock.append(0)
        else:
            self.row_origin_slot.append(self.slot(origin[0]))
            self.row_origin_clock.append(origin[1])
        if right_origin is None:
            self.row_right_slot.append(NULL)
            self.row_right_clock.append(0)
        else:
            self.row_right_slot.append(self.slot(right_origin[0]))
            self.row_right_clock.append(right_origin[1])
        self.row_is_gc.append(is_gc)
        # countable by wire ref: GC(0), ContentDeleted(1), ContentFormat(6)
        # are not countable (reference Item.js info BIT2 rules)
        self.row_countable.append(not is_gc and content_ref not in (0, 1, 6))
        self.row_content.append(content)
        self.row_content_ref.append(content_ref)
        self.row_seg.append(NULL if is_gc else seg)
        self.list_next.append(NULL)
        # membership index only for NESTED segments (the recursive
        # type-delete rule's sole consumer) — not for every root row
        if not is_gc and seg != NULL and self.seg_info[seg][2] != NULL:
            self._rows_of_seg.setdefault(seg, []).append(row)
        # content source for the native encoder
        if is_gc:
            kind, sb, so, se = SRC_NONE, NULL, NULL, NULL
        elif content_ref == 1:
            kind, sb, so, se = SRC_DELETED, NULL, NULL, NULL
        elif isinstance(content, LazyContent) and content.end >= 0:
            if content_ref == 4:
                # skip the var_string length prefix: raw UTF-8 range
                b, p = content.buf, content.ofs
                blen = 0
                shift = 0
                while True:
                    c = b[p]
                    p += 1
                    blen |= (c & 0x7F) << shift
                    shift += 7
                    if c < 0x80:
                        break
                kind, sb, so, se = SRC_UTF8, self._buf_idx(b), p, p + blen
            else:
                kind = SRC_FRAMED
                sb = self._buf_idx(content.buf)
                so, se = content.ofs, content.end
        elif isinstance(content, LazyContentV2) and content.kind == 4:
            kind = SRC_UTF8
            sb = self._buf_idx(content.buf)
            so, se = content.ofs, content.end
        else:
            kind, sb, so, se = SRC_SPILL, NULL, NULL, NULL
        self.row_src_kind.append(kind)
        self.row_src_buf.append(sb)
        self.row_src_ofs.append(so)
        self.row_src_end.append(se)
        self._gen += 1
        if is_gc:
            # GC structs are always deleted: they belong in the derived
            # DeleteSet (reference DeleteSet.js createDeleteSetFromStructStore)
            self._note_deleted(slot, clock, length)
        # fragment index insert (appends are the common case)
        fc, fr = self.frag_clock[slot], self.frag_row[slot]
        if not fc or clock > fc[-1]:
            fc.append(clock)
            fr.append(row)
        else:
            i = bisect.bisect_left(fc, clock)
            fc.insert(i, clock)
            fr.insert(i, row)
        end = clock + length
        if end > self.state[slot]:
            self.state[slot] = end
        return row

    def content_gen(self) -> int:
        """Monotonic change counter: bumps on EVERY integrated mutation
        (inserts, deletes, splits, compaction) — the cache key for
        derived views like provider.RoomUserData."""
        return self._gen

    def _frag_containing(self, slot: int, clock: int) -> int | None:
        """Index into the fragment lists of the fragment covering ``clock``."""
        fc = self.frag_clock[slot]
        i = bisect.bisect_right(fc, clock) - 1
        if i < 0:
            return None
        row = self.frag_row[slot][i]
        if clock < self.row_clock[row] + self.row_len[row]:
            return i
        return None

    def realized_content(self, row: int):
        """The row's content object, decoding the lazy payload on demand."""
        content = self.row_content[row]
        if isinstance(content, (LazyContent, LazyContentV2)):
            content = content.realize()
            self.row_content[row] = content
        return content

    def _split_existing(self, slot: int, frag_idx: int, at_clock: int, plan: StepPlan):
        """Split an integrated row so a fragment starts at ``at_clock``;
        record the link-surgery instruction for the device."""
        row = self.frag_row[slot][frag_idx]
        offset = at_clock - self.row_clock[row]
        right_content = self.realized_content(row).splice(offset)
        # the row's content is now a realized, truncated object: its lazy
        # byte range no longer matches — the encoder must re-frame it
        self.row_src_kind[row] = SRC_SPILL
        self._gen += 1
        seg = self.row_seg[row]
        new_row = self._add_row(
            slot,
            at_clock,
            self.row_len[row] - offset,
            (self.client_of_slot[slot], at_clock - 1),
            self._right_origin_of(row),
            False,
            right_content,
            self.row_content_ref[row],
            seg=seg,
        )
        self.row_len[row] = offset
        plan.splits.append((row, new_row))
        # host list splice of the fragment (device split surgery twin)
        self.list_next[new_row] = self.list_next[row]
        self.list_next[row] = new_row
        plan._dl.update((row, new_row))
        if row in self._host_deleted_rows:
            self._host_deleted_rows.add(new_row)
            # the new fragment's device deleted bit must ship too: the
            # bulk-apply path has no on-device split surgery to copy it
            # (levels/seq copy dl[orig] in their split pre-pass)
            plan.delete_rows.append(new_row)
        if seg != NULL and self.seg_is_map(seg):
            # fragments of a map-chain entry sit adjacent in its chain
            chain = self.map_chain[seg]
            chain.insert(chain.index(row) + 1, new_row)
            if row in self._lww_deleted:
                self._lww_deleted.add(new_row)
        return new_row

    def _right_origin_of(self, row: int):
        rs = self.row_right_slot[row]
        if rs == NULL:
            return None
        return (self.client_of_slot[rs], self.row_right_clock[row])

    # -- update ingestion ---------------------------------------------------

    def ingest(self, update: bytes, v2: bool = False) -> None:
        self._incoming.append((update, v2))

    def _check_supported(self, ref: ItemRef) -> None:
        if ref.is_gc:
            return
        if ref.content_ref == 9:  # ContentDoc: independent doc lifecycle
            raise UnsupportedUpdate("subdocument (content ref 9)")

    # -- map-chain host bookkeeping ----------------------------------------

    def _origin_row(self, row: int) -> int:
        """The row containing ``row``'s origin id (NULL if no origin)."""
        s = self.row_origin_slot[row]
        if s == NULL:
            return NULL
        fi = self._frag_containing(s, self.row_origin_clock[row])
        return NULL if fi is None else self.frag_row[s][fi]

    def _row_origin_eq(self, a: int, b: int) -> bool:
        sa, sb = self.row_origin_slot[a], self.row_origin_slot[b]
        return sa == sb and (
            sa == NULL or self.row_origin_clock[a] == self.row_origin_clock[b]
        )

    def _row_right_eq(self, a: int, b: int) -> bool:
        sa, sb = self.row_right_slot[a], self.row_right_slot[b]
        return sa == sb and (
            sa == NULL or self.row_right_clock[a] == self.row_right_clock[b]
        )

    def _list_insert(
        self, seg: int, row: int, left_row: int, right_row: int, plan: StepPlan
    ) -> int:
        """Resolve the row's YATA placement against the host list state and
        splice it — the host twin of the device conflict scan (reference
        Item.js:403-517, the same itemsBeforeOrigin/conflictingItems walk).
        Each flush thereby ships FINAL link values (StepPlan.link_*) and the
        default device step is one conflict-free scatter.  Returns the
        resolved left row (NULL = new head)."""
        nxt = self.list_next
        left = left_row
        o = nxt[left_row] if left_row != NULL else self.head_of_seg[seg]
        items_before: set[int] = set()
        conflicting: set[int] = set()
        while o != NULL and o != right_row:
            items_before.add(o)
            conflicting.add(o)
            if self._row_origin_eq(row, o):
                if self._row_client(o) < self._row_client(row):
                    left = o
                    conflicting.clear()
                elif self._row_right_eq(row, o):
                    break
            else:
                oor = self._origin_row(o)
                if oor != NULL and oor in items_before:
                    if oor not in conflicting:
                        left = o
                        conflicting.clear()
                else:
                    break
            o = nxt[o]
        if left != NULL:
            nxt[row] = nxt[left]
            nxt[left] = row
            plan._dl.update((left, row))
        else:
            nxt[row] = self.head_of_seg[seg]
            self.head_of_seg[seg] = row
            plan._dl.add(row)
            plan._dh.add(seg)
        return left

    def _row_client(self, row: int) -> int:
        return self.client_of_slot[self.row_slot[row]]

    def _delete_row(self, row: int, plan: StepPlan) -> None:
        """Mark one (pre-split, fully covered) row deleted with all host
        bookkeeping, recursing into the subtree when the row holds a type
        item (reference ContentType.delete, ContentType.js:106-129)."""
        if row in self._host_deleted_rows or self.row_is_gc[row]:
            return
        self._host_deleted_rows.add(row)
        plan.delete_rows.append(row)
        self._note_deleted(
            self.row_slot[row], self.row_clock[row], self.row_len[row]
        )
        plan.applied_ds.append(
            (self._row_client(row), self.row_clock[row], self.row_len[row])
        )
        sg = self.row_seg[row]
        if sg != NULL and self.seg_is_map(sg):
            self._lww_deleted.add(row)
        if self.row_content_ref[row] == 7:
            for cs in self._segs_of_parent.get(row, ()):
                for child in list(self._rows_of_seg.get(cs, ())):
                    self._delete_row(child, plan)

    def _lww_pass(self, segs: set[int], plan: StepPlan) -> None:
        """Delete every map-chain entry except the final tail (the
        order-independent net effect of reference Item.js:497-507 +
        :512-516) for each segment touched this step."""
        for seg in segs:
            chain = self.map_chain.get(seg)
            if not chain:
                continue
            tail = chain[-1]
            for r in chain:
                if r != tail and r not in self._lww_deleted:
                    self._delete_row(r, plan)

    def _segment_queries(self, frag_sched):
        """Anchor-query columns for the segment planner (ISSUE 15),
        built AFTER the pre-split pass and BEFORE any row is added:
        per-ref id/origin/rightOrigin columns plus the facts span
        eligibility needs (GC flag, content kind, explicit parent).
        Returns a :class:`~yjs_tpu.ops.segment_planner.SegmentQueries`
        of fresh arrays, or None when planning is off or the batch is
        too small to pay for kernel dispatch."""
        from . import segment_planner as _sp  # deferred: imports kernels

        n = len(frag_sched)
        if _sp.plan_segment_mode() == "off" or n < _sp.MIN_RUN:
            return None
        q = _sp.SegmentQueries()
        q.n = n
        q.client = client = np.empty(n, np.int64)
        q.clock = clock = np.empty(n, np.int64)
        q.length = length = np.empty(n, np.int64)
        q.o_cl = o_cl = np.full(n, -1, np.int64)
        q.o_ck = o_ck = np.zeros(n, np.int64)
        q.o_slot = o_slot = np.full(n, -1, np.int64)
        q.r_cl = r_cl = np.full(n, -1, np.int64)
        q.r_ck = r_ck = np.zeros(n, np.int64)
        q.r_slot = r_slot = np.full(n, -1, np.int64)
        q.gc = gc = np.zeros(n, bool)
        q.cref = cref = np.zeros(n, np.int64)
        q.pid = pid = np.zeros(n, bool)
        q.pname = pname = np.zeros(n, bool)
        slot_of = self.slot_of_client.get
        for j, ref in enumerate(frag_sched):
            client[j] = ref.client
            clock[j] = ref.clock
            length[j] = ref.length
            if ref.is_gc:
                gc[j] = True
                continue
            cref[j] = ref.content_ref
            if ref.parent_id is not None:
                pid[j] = True
            if ref.parent_name is not None:
                pname[j] = True
            if ref.origin is not None:
                c, k = ref.origin
                o_cl[j] = c
                o_ck[j] = k
                s = slot_of(c)
                if s is not None:
                    o_slot[j] = s
            if ref.right_origin is not None:
                c, k = ref.right_origin
                r_cl[j] = c
                r_ck[j] = k
                s = slot_of(c)
                if s is not None:
                    r_slot[j] = s
        return q

    def _segment_snapshot(self):
        """Slot-major snapshot of the fragment index for batched anchor
        lookup: ``(flat_slot, flat_clock, flat_row, row_len, n_slots)``.
        Per-slot runs are clock-sorted, so the composed (slot, clock)
        key is globally sorted.  This is the planner's expensive rebuild
        — the segment planner only calls it when the chain masks leave
        enough anchors unresolved (monotone prepend/typing runs reuse
        the prior per-slot sorted segments instead, ISSUE 15)."""
        import time as _time

        from ..obs.prof import kernel_profiler

        t0 = _time.perf_counter()
        sizes = [len(fc) for fc in self.frag_clock]
        total = sum(sizes)
        if total:
            flat_clock = np.concatenate(
                [np.asarray(fc, np.int64) for fc in self.frag_clock]
            )
            flat_row = np.concatenate(
                [np.asarray(fr, np.int64) for fr in self.frag_row]
            )
            flat_slot = np.repeat(
                np.arange(len(sizes), dtype=np.int64), sizes
            )
        else:
            flat_clock = np.empty(0, np.int64)
            flat_row = np.empty(0, np.int64)
            flat_slot = np.empty(0, np.int64)
        row_len = np.asarray(self.row_len, np.int64)
        kernel_profiler().record_host_op(
            "plan_snapshot", _time.perf_counter() - t0
        )
        return flat_slot, flat_clock, flat_row, row_len, len(sizes)

    # -- the flush pipeline -------------------------------------------------

    def plan_key(self, want_levels: bool | None = None,
                 want_sched: bool = True):
        """Plan-cache key for the staged work (ISSUE 9): kind + frontier
        + staged content digest + plan-shape flag."""
        return (
            "p",
            self.plan_frontier,
            _pc.staged_digest(self._incoming),
            want_levels is None or bool(want_levels),
            True,
        )

    def prepare_step(self, want_levels: bool | None = None) -> StepPlan:
        """Consume queued updates and produce the device step plan — the
        cold planning path; advances the plan frontier on success and
        poisons it on any failure (the mirror may be mid-step then, see
        the inner docstring).  Equivalent to ``prepare_step_begin()``
        followed by ``prepare_step_finish(token, "auto", …)`` — the
        engine uses the split form to co-plan whole chunks of cold docs
        in one segment-planner call (ISSUE 15)."""
        token = self.prepare_step_begin()
        return self.prepare_step_finish(token, "auto", want_levels)

    def prepare_step_begin(self):
        """Phase A of the cold plan: decode, causal scheduling, DS
        clamping, the pre-split pass, and the segment-planner query
        build.  Returns an opaque token for ``prepare_step_finish``;
        ``token.queries`` (may be None) and the mirror's
        ``_segment_snapshot`` are what :func:`segment_planner.plan_chunk`
        consumes to co-plan many docs at once.  Poisons the plan
        frontier on failure, exactly like ``prepare_step``."""
        sd = _pc.staged_digest(self._incoming)
        try:
            ctx = self._prepare_phase_a()
        except BaseException:
            self.plan_frontier = _pc.poison_frontier()
            _pc.note_invalidation("plan-error")
            raise
        ctx.sd = sd
        return ctx

    def prepare_step_finish(self, token, seg_plan,
                            want_levels: bool | None = None) -> StepPlan:
        """Phase B of the cold plan: integration (bulk fast-set runs +
        the sequential YATA fallback for the conflict residue), delete
        resolution and plan finalization.  ``seg_plan`` is the
        :class:`~yjs_tpu.ops.segment_planner.SegmentPlan` computed for
        this doc (possibly within a chunk), ``None`` to run the pure
        host walk, or ``"auto"`` to plan per-doc here.  Folds the plan
        frontier on success and poisons it on failure — together with
        ``prepare_step_begin`` this preserves ``prepare_step``'s cache
        interop exactly (device-planned results fold the same digest)."""
        try:
            if isinstance(seg_plan, str):  # "auto": per-doc planning
                from . import segment_planner as _sp

                seg_plan = _sp.plan_doc(
                    token.queries, snapshot=self._segment_snapshot
                )
            plan = self._prepare_phase_b(token, seg_plan, want_levels)
        except BaseException:
            self.plan_frontier = _pc.poison_frontier()
            _pc.note_invalidation("plan-error")
            raise
        self.plan_frontier = _pc.fold(self.plan_frontier, b"u", token.sd)
        return plan

    def _prepare_phase_a(self):
        """Decode + schedule + pre-split (phase A of the cold plan).

        Raises :class:`UnsupportedUpdate` if an incoming ref is outside the
        device path's scope (nested types, subdocuments).  The mirror may
        be left mid-step in that case — the engine demotes the doc by
        replaying its update log into a CPU Doc and discards the mirror.
        """
        incoming: dict[int, list[ItemRef]] = {}
        ds_ranges: list[tuple[int, int, int]] = list(self.pending_ds)
        for update, v2 in self._incoming:
            refs, ds = decode_update_refs(update, v2)
            for client, rs in refs.items():
                for r in rs:
                    self._check_supported(r)
                incoming.setdefault(client, []).extend(rs)
            ds_ranges.extend(ds)
        self._incoming.clear()
        self.pending_ds = []

        # merge incoming refs into the pending queues, clock-sorted
        for client, rs in incoming.items():
            q = self.pending.setdefault(client, [])
            q.extend(rs)
            q.sort(key=lambda r: r.clock)

        # -- causal scheduling (encoding.js:225-321 recast as a fixpoint) --
        sched: list[ItemRef] = []
        overlay: dict[int, int] = {}  # client -> state incl. scheduled

        def state_of(client: int) -> int:
            s = overlay.get(client)
            return self.get_state(client) if s is None else s

        def dep_ok(dep, client) -> bool:
            # reference Item.getMissing: a dep on another client is satisfied
            # once state > dep.clock (Item.js:354-397)
            return dep is None or dep[0] == client or state_of(dep[0]) > dep[1]

        progress = True
        while progress:
            progress = False
            for client in sorted(self.pending.keys(), reverse=True):
                q = self.pending[client]
                while q:
                    ref = q[0]
                    st = state_of(client)
                    if ref.clock > st:
                        break  # clock gap: wait for the missing update
                    if ref.clock + ref.length <= st:
                        q.pop(0)  # fully known: dedupe
                        progress = True
                        continue
                    if not (
                        dep_ok(ref.origin, client)
                        and dep_ok(ref.right_origin, client)
                        and dep_ok(ref.parent_id, client)
                    ):
                        # the nested-parent type item is a causal dep too
                        # (reference Item.getMissing, Item.js:354-397)
                        break
                    if ref.clock < st:
                        ref.trim_left(st - ref.clock)
                    q.pop(0)
                    sched.append(ref)
                    overlay[client] = ref.clock + ref.length
                    progress = True
        for client in [c for c, q in self.pending.items() if not q]:
            del self.pending[client]

        # -- delete-set clamping against post-step state -------------------
        # (reference DeleteSet.js:270-323: apply the known prefix, park the
        # rest in pendingDeleteReaders)
        applicable: list[tuple[int, int, int]] = []
        for client, clock, ln in ds_ranges:
            st = state_of(client)
            if clock < st:
                applicable.append((client, clock, min(ln, st - clock)))
            if clock + ln > st:
                lo = max(clock, st)
                self.pending_ds.append((client, lo, clock + ln - lo))

        # -- pre-split pass: collect every boundary the step needs ---------
        cuts: dict[int, set[int]] = {}

        def need_start(client: int, clock: int) -> None:
            cuts.setdefault(client, set()).add(clock)

        for ref in sched:
            if ref.origin is not None:
                need_start(ref.origin[0], ref.origin[1] + 1)
            if ref.right_origin is not None:
                need_start(ref.right_origin[0], ref.right_origin[1])
        for client, clock, ln in applicable:
            need_start(client, clock)
            need_start(client, clock + ln)

        plan = StepPlan(n_rows=0)
        plan._dl = set()  # rows whose list_next changed this step
        plan._dh = set()  # segs whose head changed this step

        # cuts inside scheduled refs: fragment the refs themselves
        by_client_sched: dict[int, list[int]] = {}
        for i, ref in enumerate(sched):
            by_client_sched.setdefault(ref.client, []).append(i)
        frag_sched: list[ItemRef] = []
        replacement: dict[int, list[ItemRef]] = {}
        for client, idxs in by_client_sched.items():
            ks = cuts.get(client)
            if not ks:
                continue
            ks_sorted = sorted(ks)
            for i in idxs:
                ref = sched[i]
                if ref.is_gc:
                    continue
                lo = bisect.bisect_right(ks_sorted, ref.clock)
                hi = bisect.bisect_left(ks_sorted, ref.clock + ref.length, lo)
                inner = ks_sorted[lo:hi]
                if not inner:
                    continue
                parts = [ref]
                for k in inner:
                    parts.append(parts[-1].split(k - parts[-1].clock))
                replacement[i] = parts
        for i, ref in enumerate(sched):
            frag_sched.extend(replacement.get(i, [ref]))

        # cuts inside existing rows: split + device link surgery.
        # ascending order keeps the fragment index consistent; per original
        # row the device instructions must run right-to-left, so sort the
        # emitted (row, new_row) pairs afterwards.
        pre_split_marker = len(plan.splits)
        for client, ks in cuts.items():
            slot = self.slot_of_client.get(client)
            if slot is None:
                continue
            for k in sorted(ks):
                fi = self._frag_containing(slot, k)
                if fi is None:
                    continue
                row = self.frag_row[slot][fi]
                if self.row_is_gc[row] or self.row_clock[row] == k:
                    continue  # GC runs are never split (StructStore.js:184-207)
                self._split_existing(slot, fi + 0, k, plan)
        # right-to-left per original row: new_row descending within same orig
        plan.splits[pre_split_marker:] = sorted(
            plan.splits[pre_split_marker:], key=lambda p: (p[0], -p[1])
        )

        # segment-planner queries (ISSUE 15) — built here because they
        # MUST see the post-pre-split batch and the pre-integration
        # fragment index (rows appended mid-loop are resolved by chain
        # or bisect fallback, never the snapshot)
        ctx = _PlanCtx()
        ctx.plan = plan
        ctx.frag_sched = frag_sched
        ctx.applicable = applicable
        ctx.queries = self._segment_queries(frag_sched)
        ctx.sd = None
        return ctx

    def _prepare_phase_b(self, ctx, seg_plan,
                         want_levels: bool | None = None) -> StepPlan:
        """Integration + finalization (phase B of the cold plan).

        ``seg_plan`` carries the device-computed answer: verified anchor
        hints, chain masks, and the fast-set spans integrated in bulk
        straight from the ranks; every struct it cannot place falls to
        the sequential YATA walk below — the conflict residue."""
        plan = ctx.plan
        frag_sched = ctx.frag_sched
        applicable = ctx.applicable
        q = ctx.queries
        # -- row assignment + pointer resolution ---------------------------
        hint_l = hint_r = chain_l = chain_r = None
        spans: dict[int, tuple[int, str]] = {}
        if seg_plan is not None and q is not None:
            chain_l, chain_r = seg_plan.chain_l, seg_plan.chain_r
            hint_l, hint_r = seg_plan.hint_l, seg_plan.hint_r
            spans = {s: (e, d) for s, e, d in seg_plan.spans}
        n_fastpath = 0
        seg_fast = 0
        seg_residue = 0
        prev_row = NULL  # row of frag_sched[j-1] (every branch adds one)
        touched_map_segs: set[int] = set()
        n_sched = len(frag_sched)
        j = 0
        while j < n_sched:
            ref = frag_sched[j]
            slot = self.slot(ref.client)
            if ref.is_gc:
                prev_row = self._add_row(
                    slot, ref.clock, ref.length, None, None, True, None
                )
                j += 1
                continue
            run = spans.get(j)
            left_row = right_row = NULL
            degrade = False
            if ref.origin is not None:
                if chain_l is not None:
                    if chain_l[j] and prev_row != NULL:
                        left_row = prev_row
                    elif hint_l is not None:
                        left_row = int(hint_l[j])
                if left_row == NULL:
                    oslot = self.slot(ref.origin[0])
                    fi = self._frag_containing(oslot, ref.origin[1])
                    if fi is None:
                        raise AssertionError(
                            "scheduled ref with unresolved origin"
                        )
                    left_row = self.frag_row[oslot][fi]
                if self.row_is_gc[left_row]:
                    degrade = True  # neighbour was GC'd (Item.js:380-395)
            if ref.right_origin is not None:
                if chain_r is not None:
                    if chain_r[j] and prev_row != NULL:
                        right_row = prev_row
                    elif hint_r is not None:
                        right_row = int(hint_r[j])
                if right_row == NULL:
                    rslot = self.slot(ref.right_origin[0])
                    fi = self._frag_containing(rslot, ref.right_origin[1])
                    if fi is None:
                        raise AssertionError(
                            "scheduled ref with unresolved rightOrigin"
                        )
                    right_row = self.frag_row[rslot][fi]
                if self.row_is_gc[right_row]:
                    degrade = True
            parent_row = NULL
            if not degrade and ref.parent_id is not None:
                pslot = self.slot(ref.parent_id[0])
                fi = self._frag_containing(pslot, ref.parent_id[1])
                if fi is None:
                    raise AssertionError("scheduled ref with unresolved parent")
                parent_row = self.frag_row[pslot][fi]
                if (
                    self.row_is_gc[parent_row]
                    or self.row_content_ref[parent_row] != 7
                ):
                    degrade = True  # parent type was GC'd (Item.js:380-395)
            if degrade:
                prev_row = self._add_row(
                    slot, ref.clock, ref.length, None, None, True, None
                )
                j += 1
                continue
            # segment: explicit parent, else copied from the neighbour the
            # wire omitted it for (reference encoding.js canCopyParentInfo)
            if parent_row != NULL:
                seg = self.seg(None, ref.parent_sub, parent_row)
            elif ref.parent_name is not None:
                seg = self.seg(ref.parent_name, ref.parent_sub)
            elif left_row != NULL:
                seg = self.row_seg[left_row]
            elif right_row != NULL:
                seg = self.row_seg[right_row]
            else:
                raise UnsupportedUpdate("item with no derivable parent")
            row = self._add_row(
                slot, ref.clock, ref.length, ref.origin, ref.right_origin, False,
                ref.content, ref.content_ref, seg=seg,
            )
            prev_row = row
            plan.sched.append((row, left_row, right_row, seg))
            # conflict-free fast splice: when the (left, right) gap is
            # intact, `_list_insert`'s conflict walk runs zero iterations
            # — splice inline and skip the call + per-call set churn.
            # Anything else (concurrent inserts at this gap) falls back
            # to the sequential YATA walk.
            nxt = self.list_next
            if (
                nxt[left_row] if left_row != NULL else self.head_of_seg[seg]
            ) == right_row:
                if left_row != NULL:
                    nxt[row] = nxt[left_row]
                    nxt[left_row] = row
                    plan._dl.update((left_row, row))
                else:
                    nxt[row] = self.head_of_seg[seg]
                    self.head_of_seg[seg] = row
                    plan._dl.add(row)
                    plan._dh.add(seg)
                actual_left = left_row
                n_fastpath += 1
            else:
                # conflict residue: the sequential YATA walk, now the
                # fallback for structs the segment planner cannot place
                seg_residue += 1
                actual_left = self._list_insert(
                    seg, row, left_row, right_row, plan
                )
            if self.seg_is_map(seg):
                chain = self.map_chain.setdefault(seg, [])
                if actual_left == NULL:
                    chain.insert(0, row)
                else:
                    chain.insert(chain.index(actual_left) + 1, row)
                touched_map_segs.add(seg)
            # an item integrated into a deleted parent is deleted with it
            # (reference Item.js:500-505)
            pr = self.seg_info[seg][2]
            if pr != NULL and pr in self._host_deleted_rows:
                self._delete_row(row, plan)
            if ref.content_ref == 1:  # ContentDeleted
                applicable.append((ref.client, ref.clock, ref.length))
            # fast-set bulk integration (ISSUE 15): ref j starts a
            # chained run the device ranks fully determine — verify the
            # live-state preconditions once, then splice the interior
            # without per-struct anchor resolution or walk.  Any miss
            # falls back to the scalar loop (placement cannot differ).
            if run is not None:
                e, d = run
                n_bulk, last_row = self._integrate_run(
                    frag_sched, j, e, d, seg, row, hint_r, plan
                )
                if n_bulk:
                    seg_fast += n_bulk
                    n_fastpath += n_bulk
                    prev_row = last_row
                    j = e
                    continue
            j += 1

        # -- resolve delete ranges to row ids ------------------------------
        for client, clock, ln in applicable:
            slot = self.slot_of_client.get(client)
            if slot is None:
                continue
            fc, fr = self.frag_clock[slot], self.frag_row[slot]
            i = bisect.bisect_right(fc, clock) - 1
            if i < 0:
                i = 0
            end = clock + ln
            # every covered row notes its own coverage in _delete_row (GC
            # rows at creation, earlier deletions in their own step), so no
            # range-level note is needed — it would only duplicate entries
            while i < len(fc) and fc[i] < end:
                row = fr[i]
                if fc[i] >= clock:
                    self._delete_row(row, plan)
                i += 1

        self._lww_pass(touched_map_segs, plan)
        plan.n_rows = self.n_rows
        plan.fastpath_structs = n_fastpath
        _pc.note_fastpath(n_fastpath)
        plan.segment_fast = seg_fast
        plan.segment_residue = seg_residue if seg_plan is not None else 0
        if seg_plan is not None:
            _pc.note_segment(seg_fast, plan.segment_residue)
        if want_levels is None or want_levels:
            plan.assign_levels(self._row_client)
        # finalize the bulk-apply deltas: FINAL values after all splices
        plan.link_rows = sorted(plan._dl)
        plan.link_vals = [self.list_next[r] for r in plan.link_rows]
        plan.head_segs = sorted(plan._dh)
        plan.head_vals = [self.head_of_seg[s] for s in plan.head_segs]
        # every prepare bumps the change counter even when no row was
        # appended (delete-only flushes) — the C++ twin does the same at
        # the end of Mirror::prepare, and content_gen() consumers rely
        # on it to see delete-only changes
        self._gen += 1
        return plan

    def _integrate_run(self, frag_sched, s, e, d, seg, row_s, hint_r,
                       plan):
        """Bulk-integrate the interior of a chained run straight from
        the device ranks (the ISSUE 15 fast set).

        ``frag_sched[s]`` was just integrated as ``row_s`` through the
        normal sequential path; refs ``s+1 .. e-1`` chain purely in
        direction ``d`` (statically verified by the planner: one
        client, ascending clocks, no GC/delete/explicit-parent refs).
        This verifies the LIVE-state preconditions the planner cannot
        see — root non-map segment, the splice gap actually intact, the
        shared right anchor not GC'd — and on any miss returns
        ``(0, NULL)`` so the scalar loop integrates the span instead
        (placement can never differ).  On success every interior struct
        is placed by its rank: one fragment-index append + one splice
        per row, no anchor resolution, no YATA walk."""
        if self.seg_info[seg][2] != NULL or self.seg_is_map(seg):
            return 0, NULL
        nxt = self.list_next
        right_const = NULL
        if d == "l":
            # the interior's one shared rightOrigin id, resolved once
            nref = frag_sched[s + 1]
            if nref.right_origin is not None:
                if hint_r is not None:
                    right_const = int(hint_r[s + 1])
                if right_const == NULL:
                    rslot = self.slot_of_client.get(nref.right_origin[0])
                    if rslot is None:
                        return 0, NULL
                    fi = self._frag_containing(rslot, nref.right_origin[1])
                    if fi is None:
                        return 0, NULL
                    right_const = self.frag_row[rslot][fi]
                if self.row_is_gc[right_const]:
                    return 0, NULL
            # gap: row_s must sit immediately left of the shared anchor
            if nxt[row_s] != right_const:
                return 0, NULL
        else:
            # prepend run: each interior ref must become the new head
            if self.head_of_seg[seg] != row_s:
                return 0, NULL
        slot = self.slot(frag_sched[s + 1].client)
        add_row = self._add_row
        rows = []
        for k in range(s + 1, e):
            ref = frag_sched[k]
            rows.append(add_row(
                slot, ref.clock, ref.length, ref.origin,
                ref.right_origin, False, ref.content, ref.content_ref,
                seg=seg,
            ))
        sched = plan.sched
        prev = row_s
        if d == "r":
            for row in rows:
                nxt[row] = prev
                sched.append((row, NULL, prev, seg))
                prev = row
            self.head_of_seg[seg] = prev
            plan._dl.update(rows)
            plan._dh.add(seg)
        else:
            for row in rows:
                nxt[prev] = row
                sched.append((row, prev, right_const, seg))
                prev = row
            nxt[prev] = right_const
            plan._dl.update(rows)
            plan._dl.add(row_s)
        return len(rows), prev

    def _note_deleted(self, slot: int, clock: int, ln: int) -> None:
        ranges = self.ds.setdefault(slot, [])
        ranges.append((clock, ln))
        self._ds_gen += 1

    # -- exports ------------------------------------------------------------

    # -- compaction ---------------------------------------------------------

    def rebuild_compacted_self(self, gc: bool):
        """Compact from the mirror's own list/deleted state — no device
        read-back needed (the flush invariant keeps ``list_next`` /
        ``_host_deleted_rows`` / ``head_of_seg`` equal to the device
        arrays; the YTPU_EXPORT_DEVICE test path pins that equality)."""
        n = max(1, self.n_rows)
        right = np.full(n, NULL, np.int32)
        if self.n_rows:
            right[: self.n_rows] = np.asarray(
                self.list_next[: self.n_rows], np.int32
            )
        deleted = np.zeros(n, bool)
        for r in self._host_deleted_rows:
            deleted[r] = True
        heads = (
            np.asarray(self.head_of_seg, np.int32)
            if self.n_segs
            else np.full(1, NULL, np.int32)
        )
        return self.rebuild_compacted(right, deleted, heads, gc)

    def rebuild_compacted(self, right_link, deleted, head_of_seg, gc: bool):
        """Merge adjacent runs and GC deleted payloads, renumbering rows.

        The columnar analogue of the reference's in-transaction GC + merge
        passes (tryGcDeleteSet / tryMergeDeleteSet / tryToMergeWithLeft,
        src/utils/Transaction.js:165-238): ``right_link``/``deleted`` are
        the device state read back for this doc, ``head_of_seg`` maps seg ->
        head row.  GC (when enabled) replaces deleted rows' content with a
        length-only tombstone (Item.gc parentGCd=false, Item.js:604-614);
        the merge pass collapses list-adjacent, clock-contiguous,
        origin-linked same-state rows (Item.mergeWith, Item.js:555-579).
        Map-key chains are left unmerged (tiny by construction).

        Returns (new_right, new_deleted, new_head_of_seg) numpy arrays over
        the NEW row numbering for device re-upload.
        """
        from ..core import ContentDeleted

        n = self.n_rows
        # per-seg order by walking the read-back links
        order_of_seg: dict[int, list[int]] = {}
        for seg in range(self.n_segs):
            head = int(head_of_seg[seg]) if seg < len(head_of_seg) else NULL
            out = []
            r = head
            while r != NULL:
                out.append(r)
                r = int(right_link[r])
            order_of_seg[seg] = out

        # GC pass: deleted content -> tombstone (payload freed)
        if gc:
            for row in range(n):
                if (
                    not self.row_is_gc[row]
                    and deleted[row]
                    and self.row_content_ref[row] != 1
                ):
                    self.row_content[row] = ContentDeleted(self.row_len[row])
                    self.row_content_ref[row] = 1
                    self.row_countable[row] = False
                    self.row_src_kind[row] = SRC_DELETED

        # merge pass: list segments right-to-left; GC rows by clock order
        absorbed: dict[int, int] = {}  # dead row -> surviving head row

        def try_merge(a: int, b: int) -> bool:
            if self.row_slot[a] != self.row_slot[b]:
                return False
            if self.row_clock[a] + self.row_len[a] != self.row_clock[b]:
                return False
            if bool(deleted[a]) != bool(deleted[b]):
                return False
            if self.row_is_gc[a] != self.row_is_gc[b]:
                return False
            if b in self._segs_of_parent or a in self._segs_of_parent:
                # a nested segment's parent row must keep its identity —
                # absorbing it would orphan its children's wire parent id
                # (even after the GC pass tombstones the type's content)
                return False
            if self.row_is_gc[a]:
                return True  # GC runs merge on contiguity alone (GC.js:24-27)
            # right.origin == this.lastId
            if self.row_origin_slot[b] != self.row_slot[a] or (
                self.row_origin_clock[b]
                != self.row_clock[a] + self.row_len[a] - 1
            ):
                return False
            if not self._row_right_eq(a, b):
                return False
            ca, cb = self.realized_content(a), self.realized_content(b)
            if type(ca) is not type(cb) or not ca.merge_with(cb):
                return False
            return True

        for seg, order in order_of_seg.items():
            if self.seg_is_map(seg):
                continue
            i = 0
            while i + 1 < len(order):
                a, b = order[i], order[i + 1]
                if try_merge(a, b):
                    self.row_len[a] += self.row_len[b]
                    if self.row_src_kind[a] != SRC_DELETED:
                        self.row_src_kind[a] = SRC_SPILL  # merged content
                    absorbed[b] = a
                    order.pop(i + 1)
                else:
                    i += 1
        # GC structs: not in any list; merge contiguous runs per client
        for slot in range(len(self.client_of_slot)):
            prev = None
            for row in self.frag_row[slot]:
                if not self.row_is_gc[row] or row in absorbed:
                    prev = None if not self.row_is_gc[row] else row
                    continue
                if prev is not None and try_merge(prev, row):
                    self.row_len[prev] += self.row_len[row]
                    absorbed[row] = prev
                else:
                    prev = row

        # renumber surviving rows (order preserved: absorbed rows vanish)
        new_of_old = np.full(n, NULL, np.int32)
        keep = [r for r in range(n) if r not in absorbed]
        for new, old in enumerate(keep):
            new_of_old[old] = new
        self._renumber(keep, new_of_old)

        n_new = len(keep)
        new_right = np.full(n_new, NULL, np.int32)
        new_deleted = np.zeros(n_new, bool)
        new_heads = np.full(max(1, self.n_segs), NULL, np.int32)
        for old in keep:
            new_deleted[new_of_old[old]] = bool(deleted[old])
        for seg, order in order_of_seg.items():
            prev = NULL
            for old in order:
                nr = new_of_old[old]
                if prev == NULL:
                    new_heads[seg] = nr
                else:
                    new_right[prev] = nr
                prev = nr
        self.list_next = new_right.tolist()
        self.head_of_seg = new_heads[: self.n_segs].tolist()
        # deterministic fold over the compaction inputs: same inputs ->
        # same chain, anything else diverges (plan-cache keying)
        self.plan_frontier = _pc.fold(
            self.plan_frontier,
            b"compact",
            np.ascontiguousarray(right_link, np.int32).tobytes()
            + np.ascontiguousarray(deleted, np.uint8).tobytes()
            + np.ascontiguousarray(head_of_seg, np.int32).tobytes()
            + (b"g" if gc else b"-"),
        )
        _pc.note_invalidation("compact")
        return new_right, new_deleted, new_heads

    def _renumber(self, keep: list[int], new_of_old: np.ndarray) -> None:
        """Apply a row renumbering to every host-side structure."""
        take = lambda col: [col[r] for r in keep]
        self.row_slot = take(self.row_slot)
        self.row_clock = take(self.row_clock)
        self.row_len = take(self.row_len)
        self.row_origin_slot = take(self.row_origin_slot)
        self.row_origin_clock = take(self.row_origin_clock)
        self.row_right_slot = take(self.row_right_slot)
        self.row_right_clock = take(self.row_right_clock)
        self.row_is_gc = take(self.row_is_gc)
        self.row_countable = take(self.row_countable)
        self.row_content = take(self.row_content)
        self.row_content_ref = take(self.row_content_ref)
        self.row_seg = take(self.row_seg)
        self.row_src_kind = take(self.row_src_kind)
        self.row_src_buf = take(self.row_src_buf)
        self.row_src_ofs = take(self.row_src_ofs)
        self.row_src_end = take(self.row_src_end)
        # prune the source-buffer registry: compaction tombstones/merges
        # rows, and buffers no surviving row references must not stay
        # pinned for the mirror's lifetime
        used = sorted({b for b in self.row_src_buf if b >= 0})
        remap = {old: new for new, old in enumerate(used)}
        self._bufs = [self._bufs[b] for b in used]
        self._buf_ids = {id(b): j for j, b in enumerate(self._bufs)}
        self.row_src_buf = [
            remap[b] if b >= 0 else b for b in self.row_src_buf
        ]
        self._gen += 1
        # fragment index: rebuild from the surviving rows (clock-sorted)
        n_slots = len(self.client_of_slot)
        self.frag_clock = [[] for _ in range(n_slots)]
        self.frag_row = [[] for _ in range(n_slots)]
        by_slot: dict[int, list[int]] = {}
        for row in range(len(self.row_slot)):
            by_slot.setdefault(self.row_slot[row], []).append(row)
        for slot, rows in by_slot.items():
            rows.sort(key=lambda r: self.row_clock[r])
            self.frag_clock[slot] = [self.row_clock[r] for r in rows]
            self.frag_row[slot] = rows
        self.map_chain = {
            seg: [int(new_of_old[r]) for r in chain]
            for seg, chain in self.map_chain.items()
        }
        self._lww_deleted = {
            int(new_of_old[r]) for r in self._lww_deleted if new_of_old[r] != NULL
        }
        self._host_deleted_rows = {
            int(new_of_old[r])
            for r in self._host_deleted_rows
            if new_of_old[r] != NULL
        }
        # nested-segment bookkeeping: parent rows renumber; type rows are
        # never absorbed (ContentType does not merge), so parents survive
        self._rows_of_seg = {
            seg: [int(new_of_old[r]) for r in rows if new_of_old[r] != NULL]
            for seg, rows in self._rows_of_seg.items()
        }
        remap_parent = (
            lambda p: p if p == NULL else int(new_of_old[p])
        )
        self.seg_info = [
            (name, sub, remap_parent(p)) for name, sub, p in self.seg_info
        ]
        self.segments = {key: s for s, key in enumerate(self.seg_info)}
        self._segs_of_parent = {}
        for s, (_n, _s2, p) in enumerate(self.seg_info):
            if p != NULL:
                self._segs_of_parent.setdefault(p, []).append(s)
        # compact the host DS ranges too (sorted union)
        for slot, ranges in self.ds.items():
            self.ds[slot] = self._union_ranges(ranges)

    def state_vector(self) -> dict[int, int]:
        return {
            self.client_of_slot[s]: st for s, st in enumerate(self.state) if st > 0
        }

    def encode_state_vector(self) -> bytes:
        from ..coding import DSEncoderV1
        from ..updates import write_state_vector

        encoder = DSEncoderV1()
        write_state_vector(encoder, self.state_vector())
        return encoder.to_bytes()

    @staticmethod
    def _union_ranges(ranges) -> list[tuple[int, int]]:
        """Sorted union of (clock, len) ranges.  The mirror's bookkeeping
        may note overlapping coverage (per-row deletes + remote DS ranges);
        the wire DS must be disjoint — the reference's sortAndMergeDeleteSet
        only coalesces exactly-touching ranges because its inputs are
        disjoint by construction (DeleteSet.js:113-135)."""
        out: list[tuple[int, int]] = []
        for clock, ln in sorted(ranges):
            if out and clock <= out[-1][0] + out[-1][1]:
                last_c, last_l = out[-1]
                out[-1] = (last_c, max(last_l, clock + ln - last_c))
            else:
                out.append((clock, ln))
        return out

    def delete_set(self):
        """The doc's derived DeleteSet (reference
        createDeleteSetFromStructStore, DeleteSet.js:185-210)."""
        from ..core import DeleteItem, DeleteSet

        ds = DeleteSet()
        for slot, ranges in self.ds.items():
            ds.clients[self.client_of_slot[slot]] = [
                DeleteItem(clock, ln)
                for clock, ln in self._union_ranges(ranges)
            ]
        return ds

    def encode_state_as_update(self, target_sv: dict[int, int] | None = None,
                               v2: bool = False) -> bytes:
        """Wire-encode this doc's missing state directly from the columns —
        the columnar writeStateAsUpdate (reference encoding.js:490-493,
        writeClientsStructs :94-116, Item.write Item.js:625-658).

        Emitted runs follow the mirror's fragmentation (never re-merged);
        the update is byte-valid and state-equivalent, like any Yjs update.
        """
        target_sv = target_sv or {}
        needed, offset = self._diff_mask(target_sv)
        return self.encode_masked_update(needed, offset, v2=v2)

    def _diff_mask(self, remote_sv: dict[int, int]):
        """Vectorized host twin of kernels.diff_mask_kernel: rows (or row
        suffixes) beyond a remote state vector (encoding.js:94-116)."""
        n = self.n_rows
        if n == 0:
            return np.zeros(0, bool), np.zeros(0, np.int64)
        c = self._np_cols()
        remote_of_slot = np.asarray(
            [remote_sv.get(cl, 0) for cl in self.client_of_slot], np.int64
        )
        remote = remote_of_slot[np.asarray(self.row_slot, np.int64)]
        needed = c["row_end"] > remote
        offset = np.where(needed, np.clip(remote - c["clock"], 0, None), 0)
        return needed, offset

    def encode_step_update(self, pre_sv: dict[int, int], plan: StepPlan,
                           v2: bool = False) -> bytes | None:
        """The incremental update one flush produced: structs beyond the
        pre-flush state vector + the step's applied delete ranges — the
        engine's doc.on('update') payload (reference Transaction.js:339-352
        emits exactly the transaction's novelty)."""
        needed, offset = self._diff_mask(pre_sv)
        if not needed.any() and not plan.applied_ds:
            return None
        return self.encode_masked_update(
            needed, offset, v2=v2, ds_ranges=plan.applied_ds
        )

    def encode_masked_update(self, needed, offset, v2: bool = False,
                             ds_ranges=None) -> bytes:
        """Wire-encode the rows selected by ``needed`` (bool [n_rows]) from
        element ``offset`` — the writer half of sync step 2, fed either by
        the host mask above or by the device ``diff_mask_kernel`` for the
        engine's batched path.  ``ds_ranges`` overrides the DS section
        (defaults to the doc's full derived DeleteSet)."""
        from ..coding import UpdateEncoderV1, UpdateEncoderV2
        from ..core import write_delete_set
        from ..lib0 import encoding as lib0enc

        if not v2:
            from ..native import NativeDecodeError

            try:
                return self._encode_masked_update_native(
                    needed, offset, ds_ranges
                )
            except NativeDecodeError:
                pass  # no toolchain: pure-Python writer below

        encoder = UpdateEncoderV2() if v2 else UpdateEncoderV1()
        # clients with news, descending id ("heavily improves the conflict
        # algorithm", reference encoding.js:112)
        todo = []
        for slot in range(len(self.client_of_slot)):
            rows = [r for r in self.frag_row[slot] if r < len(needed) and needed[r]]
            if rows:
                todo.append((self.client_of_slot[slot], rows))
        todo.sort(reverse=True)
        lib0enc.write_var_uint(encoder.rest_encoder, len(todo))
        for client, rows in todo:
            lib0enc.write_var_uint(encoder.rest_encoder, len(rows))
            encoder.write_client(client)
            first_ofs = int(offset[rows[0]])
            lib0enc.write_var_uint(
                encoder.rest_encoder, self.row_clock[rows[0]] + first_ofs
            )
            for j, row in enumerate(rows):
                self._write_row(encoder, row, first_ofs if j == 0 else 0)
        if ds_ranges is None:
            ds = self.delete_set()
        else:
            from ..core import DeleteItem, DeleteSet

            by_client: dict[int, list[tuple[int, int]]] = {}
            for client, clock, ln in ds_ranges:
                by_client.setdefault(client, []).append((clock, ln))
            ds = DeleteSet()
            for client, ranges in by_client.items():
                ds.clients[client] = [
                    DeleteItem(c, ln) for c, ln in self._union_ranges(ranges)
                ]
        write_delete_set(encoder, ds)
        return encoder.to_bytes()

    def _np_cols(self) -> dict[str, np.ndarray]:
        """Numpy views of the encode-relevant row columns, rebuilt only when
        the mirror mutated since the last build (generation counter)."""
        if self._np_gen == self._gen:
            return self._np
        client_of_slot = np.asarray(self.client_of_slot, np.int64)
        resolve = lambda slots: np.where(
            slots >= 0, client_of_slot[np.clip(slots, 0, None)], NULL
        )
        oslot = np.asarray(self.row_origin_slot, np.int64)
        rslot = np.asarray(self.row_right_slot, np.int64)
        seg = np.asarray(self.row_seg, np.int64)
        safe_seg = np.clip(seg, 0, None)
        seg_gather = lambda col, fill: np.where(
            seg >= 0,
            np.asarray(col, np.int64)[safe_seg] if len(col) else NULL,
            fill,
        )
        c = {
            "slot": np.asarray(self.row_slot, np.int64),
            "client": resolve(np.asarray(self.row_slot, np.int64)),
            "clock": np.asarray(self.row_clock, np.int64),
            "length": np.asarray(self.row_len, np.int64),
            "origin_client": resolve(oslot),
            "origin_clock": np.asarray(self.row_origin_clock, np.int64),
            "right_client": resolve(rslot),
            "right_clock": np.asarray(self.row_right_clock, np.int64),
            "content_ref": np.asarray(self.row_content_ref, np.int64),
            "src_kind": np.asarray(self.row_src_kind, np.int64),
            "src_buf": np.asarray(self.row_src_buf, np.int64),
            "src_ofs": np.asarray(self.row_src_ofs, np.int64),
            "src_end": np.asarray(self.row_src_end, np.int64),
            "name_ofs": seg_gather(self.seg_name_ofs, NULL),
            "name_len": seg_gather(self.seg_name_len, 0),
            "sub_ofs": seg_gather(self.seg_sub_ofs, NULL),
            "sub_len": seg_gather(self.seg_sub_len, 0),
        }
        # nested-segment parents: each row's parent type item id (NULL root)
        p_row = seg_gather([p for _n, _s, p in self.seg_info], NULL)
        safe_p = np.clip(p_row, 0, None)
        c["parent_client"] = np.where(p_row >= 0, c["client"][safe_p], NULL)
        c["parent_clock"] = np.where(p_row >= 0, c["clock"][safe_p], 0)
        c["row_end"] = c["clock"] + c["length"]
        # write order: client descending, clock ascending (encoding.js:112)
        c["order"] = np.lexsort((c["clock"], -c["client"]))
        self._np = c
        self._np_gen = self._gen
        return c

    def _encode_masked_update_native(self, needed, offset,
                                     ds_ranges=None) -> bytes:
        """Gather the masked rows from the cached numpy columns and let the
        C++ writer assemble the V1 update (ytpu_encode_v1).  Content bytes
        memcpy straight from the source update buffers the rows were decoded
        from (LazyContent / V2 arena ranges, precomputed at row creation);
        realized or partially-written non-string contents are pre-framed
        into a spill buffer by the Python encoder."""
        from ..coding import UpdateEncoderV1
        from ..native import NativeDecodeError, encode_v1_update, load

        if load() is None:
            raise NativeDecodeError("native transcoder unavailable")

        c = self._np_cols()
        n_rows = len(c["clock"])
        needed = np.asarray(needed, bool)
        offset = np.asarray(offset, np.int64)
        if len(needed) < n_rows:
            needed = np.pad(needed, (0, n_rows - len(needed)))
            offset = np.pad(offset, (0, n_rows - len(offset)))
        order = c["order"]
        sel = order[needed[order]]
        n = len(sel)
        cols = {
            k: c[k][sel]
            for k in (
                "clock", "length", "origin_client", "origin_clock",
                "right_client", "right_clock", "content_ref",
                "name_ofs", "name_len", "sub_ofs", "sub_len",
                "parent_client", "parent_clock",
                "src_kind", "src_buf", "src_ofs", "src_end",
            )
        }
        cols["offset"] = offset[sel]
        sel_client = c["client"][sel]

        # client groups: contiguous runs in the descending-client order
        if n:
            bounds = np.flatnonzero(np.diff(sel_client) != 0) + 1
            group_start = np.concatenate(([0], bounds))
            group_len = np.diff(np.concatenate((group_start, [n])))
            group_client = sel_client[group_start]
        else:
            group_start = group_len = group_client = np.zeros(0, np.int64)

        # spill pass: realized contents, partial non-string first structs,
        # and V2-framed payloads that have no V1-compatible byte range
        from ..native import SRC_V2LAZY

        spill_idx = np.flatnonzero(
            (cols["src_kind"] == SRC_SPILL)
            | (cols["src_kind"] == SRC_V2LAZY)
            | ((cols["src_kind"] == SRC_FRAMED) & (cols["offset"] > 0))
        )
        spill = UpdateEncoderV1()
        spill_buf = spill.rest_encoder.buf
        for j in spill_idx:
            row = int(sel[j])
            pos0 = len(spill_buf)
            self.realized_content(row).write(spill, int(cols["offset"][j]))
            cols["src_kind"][j] = SRC_SPILL
            cols["src_ofs"][j] = pos0
            cols["src_end"][j] = len(spill_buf)
        bufs = list(self._bufs)
        spill_id = len(bufs)
        bufs.append(bytes(spill_buf))
        if len(spill_idx):
            cols["src_buf"][spill_idx] = spill_id

        content_bytes = int(
            np.sum(
                np.where(
                    cols["src_end"] >= 0, cols["src_end"] - cols["src_ofs"], 10
                )
            )
            + np.sum(cols["name_len"])
            + np.sum(cols["sub_len"])
        ) if n else 0
        strings = self._strings

        # DS section groups (write_delete_set order: dict iteration)
        if ds_ranges is None:
            (ds_group_client, ds_group_start, ds_group_len,
             ds_clock, ds_len) = self._merged_ds_arrays()
        else:
            by_client: dict[int, list[tuple[int, int]]] = {}
            for client, clock, ln in ds_ranges:
                by_client.setdefault(client, []).append((clock, ln))
            merged = {
                client: self._union_ranges(ranges)
                for client, ranges in by_client.items()
            }
            ds_group_client = np.asarray(list(merged.keys()), np.int64)
            ds_group_len = np.asarray(
                [len(v) for v in merged.values()], np.int64
            )
            ds_group_start = np.zeros(len(merged), np.int64)
            if len(merged) > 1:
                ds_group_start[1:] = np.cumsum(ds_group_len)[:-1]
            ds_clock = np.asarray(
                [c for v in merged.values() for c, _l in v], np.int64
            )
            ds_len = np.asarray(
                [ln for v in merged.values() for _c, ln in v], np.int64
            )

        out_cap = (
            64
            + n * 80
            + content_bytes
            + 24 * (len(ds_clock) + len(ds_group_client))
        )
        return encode_v1_update(
            bufs,
            group_client, group_start, group_len,
            cols,
            bytes(strings),
            ds_group_client, ds_group_start, ds_group_len,
            ds_clock, ds_len,
            out_cap,
        )

    def _merged_ds_arrays(self):
        """The doc's derived DeleteSet as grouped, sorted+merged numpy
        arrays (DeleteSet.js:113-135 semantics, vectorized and cached)."""
        if self._ds_np_gen == self._ds_gen and self._ds_np is not None:
            return self._ds_np
        g_client, g_start, g_len = [], [], []
        clocks, lens = [], []
        pos = 0
        for slot, ranges in self.ds.items():
            if not ranges:
                continue
            a = np.asarray(ranges, np.int64).reshape(-1, 2)
            o = np.argsort(a[:, 0], kind="stable")
            cl, ln = a[o, 0], a[o, 1]
            end = cl + ln
            cummax = np.maximum.accumulate(end)
            # new interval iff start > max end of everything before it
            new_g = np.empty(len(cl), bool)
            new_g[0] = True
            new_g[1:] = cl[1:] > cummax[:-1]
            idx = np.flatnonzero(new_g)
            m_start = cl[idx]
            last = np.concatenate((idx[1:] - 1, [len(cl) - 1]))
            m_end = cummax[last]
            g_client.append(self.client_of_slot[slot])
            g_start.append(pos)
            g_len.append(len(idx))
            pos += len(idx)
            clocks.append(m_start)
            lens.append(m_end - m_start)
        out = (
            np.asarray(g_client, np.int64),
            np.asarray(g_start, np.int64),
            np.asarray(g_len, np.int64),
            np.concatenate(clocks) if clocks else np.zeros(0, np.int64),
            np.concatenate(lens) if lens else np.zeros(0, np.int64),
        )
        self._ds_np_gen = self._ds_gen
        self._ds_np = out
        return out

    def _write_row(self, encoder, row: int, offset: int) -> None:
        """Wire-encode one row (reference Item.js:625-658 / GC.js:45-48)."""
        from ..ids import create_id

        if self.row_is_gc[row]:
            encoder.write_info(0)
            encoder.write_len(self.row_len[row] - offset)
            return
        oslot = self.row_origin_slot[row]
        rslot = self.row_right_slot[row]
        if offset > 0:
            origin = create_id(
                self.client_of_slot[self.row_slot[row]],
                self.row_clock[row] + offset - 1,
            )
        elif oslot != NULL:
            origin = create_id(self.client_of_slot[oslot], self.row_origin_clock[row])
        else:
            origin = None
        right = (
            create_id(self.client_of_slot[rslot], self.row_right_clock[row])
            if rslot != NULL
            else None
        )
        name, sub, parent_row = self.seg_info[self.row_seg[row]]
        ref = self.row_content_ref[row]
        info = (
            ref
            | (0 if origin is None else BIT8)
            | (0 if right is None else BIT7)
            | (0 if sub is None else BIT6)
        )
        encoder.write_info(info)
        if origin is not None:
            encoder.write_left_id(origin)
        if right is not None:
            encoder.write_right_id(right)
        if origin is None and right is None:
            if parent_row != NULL:
                # nested type: parent is the type item's id (Item.js:644-648)
                encoder.write_parent_info(False)
                encoder.write_left_id(
                    create_id(
                        self.client_of_slot[self.row_slot[parent_row]],
                        self.row_clock[parent_row],
                    )
                )
            else:
                encoder.write_parent_info(True)  # root-type key parent
                encoder.write_string(name)
            if sub is not None:
                encoder.write_string(sub)
        self.realized_content(row).write(encoder, offset)

    def origin_rows(self, start: int = 0) -> np.ndarray:
        """For rows [start:], the row *containing* each origin id (NULL if
        no origin) — the columnar get_item(store, o.origin) of the case-2
        conflict check (reference src/structs/Item.js:447-470)."""
        n = self.n_rows
        out = np.full(n - start, NULL, np.int32)
        oslot = np.asarray(self.row_origin_slot[start:], np.int32)
        oclock = np.asarray(self.row_origin_clock[start:], np.int64)
        for s in range(len(self.client_of_slot)):
            mask = oslot == s
            if not mask.any():
                continue
            fc = np.asarray(self.frag_clock[s], np.int64)
            fr = np.asarray(self.frag_row[s], np.int32)
            idx = np.searchsorted(fc, oclock[mask], side="right") - 1
            out[np.nonzero(mask)[0]] = fr[np.clip(idx, 0, len(fr) - 1)]
        return out

    def static_columns(self, start: int = 0) -> dict[str, np.ndarray]:
        """The immutable device columns for rows [start:] — host cost scales
        with the delta when the caller keeps earlier rows resident."""
        return {
            "client_key": np.asarray(
                [self.client_of_slot[s] for s in self.row_slot[start:]],
                np.uint32,
            ),
            "origin_slot": np.asarray(self.row_origin_slot[start:], np.int32),
            "origin_clock": np.asarray(self.row_origin_clock[start:], np.int32),
            "right_slot": np.asarray(self.row_right_slot[start:], np.int32),
            "right_clock": np.asarray(self.row_right_clock[start:], np.int32),
            "origin_row": self.origin_rows(start),
        }

    def has_pending(self) -> bool:
        return bool(self.pending) or bool(self.pending_ds)

    def pending_depth(self) -> int:
        """Parked refs + delete ranges awaiting causal deps (metrics)."""
        return sum(len(q) for q in self.pending.values()) + len(self.pending_ds)
