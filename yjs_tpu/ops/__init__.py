"""TPU-native batch ops: columnar transcoding + JAX kernels + BatchEngine."""

from .batch import (  # noqa: F401
    diff_update_columnar,
    encode_state_vector_from_update_columnar,
    merge_updates_columnar,
)
from .columns import DocMirror, ItemRef, StepPlan, UnsupportedUpdate, decode_update_refs  # noqa: F401
from .engine import BatchEngine  # noqa: F401
