"""TPU-native batch ops: columnar transcoding + JAX kernels + BatchEngine."""

from .columns import DocMirror, ItemRef, StepPlan, UnsupportedUpdate, decode_update_refs  # noqa: F401
from .engine import BatchEngine  # noqa: F401
