"""Device-authoritative cold planning (ISSUE 15).

The PR 9 segment-sorted kernels resolved anchors as verified *hints*
feeding the sequential host walk.  This module promotes them to the
authoritative cold planner:

- one conflict scan over the (doc, client, clock)-sorted flush batch
  detects chained runs (typing runs, prepend storms) — the device rank
  of each chained struct IS its placement, no per-struct walk;
- one composed-key searchsorted resolves every remaining anchor in the
  whole flush chunk at once (all cold docs co-planned in a single
  batched kernel call, sharded over the doc mesh via ``shard_map`` when
  the engine runs meshed);
- the structs the scan cannot chain form the *conflict residue* — the
  only structs handed to the sequential YATA walk, now a fallback.

Modes (``YTPU_PLAN_SEGMENT``):

========  ==================================================
device    default: whole-chunk planning on the jitted kernels,
          sharded over the doc mesh when one is configured
np        per-doc planning on the NumPy kernel twins
jax       per-doc planning on the jitted kernels
off       pure sequential host walk (the A/B lane)
========  ==================================================

Donation safety: every array this module returns is freshly allocated
host memory (``np.asarray`` copies of kernel outputs, ``np.full``
pads) — never a view of the engine's donated column tables, so a plan
outliving its flush can never alias a buffer the device has since
repurposed.

Monotone-run snapshot reuse (ISSUE 15 bugfix): when the conflict scan
chains all but a handful of anchors (pure head-prepend / typing runs),
rebuilding the flat slot-major snapshot of the fragment index — a full
re-sort's worth of concatenation per flush — buys nothing.  The planner
detects that case and leaves those few anchors to the caller's per-slot
bisect against the *prior sorted segments* (the fragment index is
already clock-sorted per slot), skipping the snapshot entirely.
"""

from __future__ import annotations

import functools
import os

import numpy as np

from . import kernels
from . import plan_cache as _pc

NULL = -1  # must match yjs_tpu.ops.columns.NULL

MODES = ("device", "np", "jax", "off")
_DEFAULT_MODE = "device"

# at or below this many unresolved anchors the planner reuses the
# per-slot sorted fragment segments directly (caller-side bisect per
# anchor) instead of rebuilding the flat snapshot
SNAPSHOT_SKIP_MAX = 8

# a chained run shorter than this is not worth bulk integration
MIN_RUN = 4


def plan_segment_mode() -> str:
    """Resolve ``YTPU_PLAN_SEGMENT`` to a known mode (default: device)."""
    mode = os.environ.get("YTPU_PLAN_SEGMENT", _DEFAULT_MODE)
    return mode if mode in MODES else _DEFAULT_MODE


def _bucket_pow2(n: int, minimum: int = 64) -> int:
    """Next power-of-two lane width >= n: query/snapshot lengths are
    unique per chunk, so jitted kernel shapes must quantize or every
    flush retraces."""
    b = minimum
    while b < n:
        b <<= 1
    return b


def _pad_pow2(arr: np.ndarray, n_pad: int, fill) -> np.ndarray:
    """``arr`` padded to the bucketed length with ``fill`` (fresh
    allocation — never a view of caller memory)."""
    out = np.full(n_pad, fill, arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class SegmentQueries:
    """Anchor-query columns for one doc's flush batch, built by
    ``DocMirror._segment_queries`` after the pre-split pass.

    ``o_*`` / ``r_*`` mirror origin / rightOrigin: client -1 means the
    anchor is absent, slot -1 means the anchor's client has no slot
    (resolved by the caller's bisect fallback).  ``gc``, ``cref``,
    ``pid`` and ``pname`` carry the per-ref facts span eligibility
    needs (GC tombstone, content kind, explicit parent id / name).
    """

    __slots__ = (
        "n", "client", "clock", "length",
        "o_cl", "o_ck", "o_slot", "r_cl", "r_ck", "r_slot",
        "gc", "cref", "pid", "pname",
    )


class SegmentPlan:
    """One doc's device-planned cold-path answer.

    ``hint_l`` / ``hint_r`` are verified candidate anchor rows
    (``NULL`` = resolve by bisect) or ``None`` when the snapshot was
    skipped entirely; ``chain_l`` / ``chain_r`` / ``run_id`` are the
    conflict-scan chain masks; ``spans`` lists the maximal
    single-direction chained runs eligible for bulk integration as
    ``(start, end, direction)`` with direction ``'l'`` (left chains to
    the previous ref, typing runs) or ``'r'`` (right chains, prepend
    runs).  All arrays are fresh host memory.
    """

    __slots__ = (
        "hint_l", "hint_r", "chain_l", "chain_r", "run_id", "spans",
        "snapshot_reused",
    )


def _scan_doc(q: SegmentQueries, backend: str):
    """Per-doc conflict scan (bucketed when jitted)."""
    if backend != "jax":
        return kernels.plan_conflict_scan(
            q.client, q.clock, q.length, q.o_cl, q.o_ck, q.r_cl, q.r_ck,
            backend="np",
        )
    nb = _bucket_pow2(q.n)
    l, r, g = kernels._conflict_scan_jax(
        _pad_pow2(q.client, nb, -1),
        _pad_pow2(q.clock, nb, 0),
        _pad_pow2(q.length, nb, 0),
        _pad_pow2(q.o_cl, nb, -1),
        _pad_pow2(q.o_ck, nb, 0),
        _pad_pow2(q.r_cl, nb, -1),
        _pad_pow2(q.r_ck, nb, 0),
    )
    n = q.n
    return (
        np.asarray(l)[:n],
        np.asarray(r)[:n],
        np.asarray(g)[:n],
    )


def _chain_spans(q: SegmentQueries, chain_l, chain_r, run_id):
    """Maximal single-direction chained spans eligible for bulk
    integration straight from device ranks.

    A span ``(s, e, d)`` promises: refs ``s+1 .. e-1`` chain purely in
    direction ``d`` onto their predecessor, are non-GC non-delete
    content from one client with strictly ascending clocks, carry no
    explicit parent, and (for ``'l'``) share one rightOrigin id.  The
    caller integrates ref ``s`` through the normal sequential path,
    verifies the live-state preconditions, then splices the interior in
    one pass — any precondition miss simply falls back to the scalar
    loop (the residue), so placement can never differ.
    """
    n = q.n
    if n < MIN_RUN:
        return []
    chained = chain_l | chain_r
    spans = []
    # run starts: positions where the chain breaks
    starts = np.flatnonzero(~chained)
    bounds = np.append(starts, n)
    for si in range(len(starts)):
        s, e = int(bounds[si]), int(bounds[si + 1])
        if e - s < MIN_RUN:
            continue
        il, ir = chain_l[s + 1 : e], chain_r[s + 1 : e]
        if il.all() and not ir.any():
            d = "l"
        elif ir.all() and not il.any():
            d = "r"
        else:
            continue  # mixed-direction run: scalar loop handles it
        sl = slice(s, e)
        if q.gc[sl].any() or q.pid[sl].any():
            continue
        if (q.cref[sl] == 1).any():  # ContentDeleted feeds delete ranges
            continue
        if q.pname[s + 1 : e].any():  # interior must copy the neighbour seg
            continue
        if not (q.client[sl] == q.client[s]).all():
            continue
        if not (np.diff(q.clock[sl]) > 0).all():
            continue  # fragment-index append needs ascending clocks
        if d == "r":
            # prepend run: interior origins must be absent (left = NULL)
            if (q.o_cl[s + 1 : e] != -1).any():
                continue
        else:
            # typing run: one shared rightOrigin id across the interior
            if not (
                (q.r_cl[s + 1 : e] == q.r_cl[s + 1]).all()
                and (q.r_ck[s + 1 : e] == q.r_ck[s + 1]).all()
            ):
                continue
        spans.append((s, e, d))
    return spans


def _verify_hints(cand, q_slot, q_ck, flat_slot, flat_clock, flat_row,
                  row_len):
    """Containment check: a candidate only becomes a hint when the live
    columns confirm the queried clock lies inside the candidate row."""
    total = flat_clock.shape[0]
    if total == 0:
        return np.full(q_slot.shape[0], NULL, np.int64)
    safe = np.clip(cand, 0, total - 1)
    c_row = flat_row[safe]
    ok = (
        (cand >= 0)
        & (q_slot >= 0)
        & (flat_slot[safe] == q_slot)
        & (q_ck >= flat_clock[safe])
        & (q_ck < flat_clock[safe] + row_len[c_row])
    )
    return np.where(ok, c_row, NULL)


def _needed(q: SegmentQueries, chain_l, chain_r) -> int:
    """Anchors the chain masks do NOT cover — the only ones a snapshot
    lookup could resolve."""
    need_l = int(((q.o_slot >= 0) & ~chain_l).sum())
    need_r = int(((q.r_slot >= 0) & ~chain_r).sum())
    return need_l + need_r


def plan_doc(q: SegmentQueries | None, mode: str | None = None,
             snapshot=None) -> SegmentPlan | None:
    """Plan one doc's flush batch.  ``snapshot`` is a zero-arg callable
    returning ``(flat_slot, flat_clock, flat_row, row_len, n_slots)``
    (the slot-major fragment-index snapshot); it is only invoked when
    the chain masks leave enough anchors unresolved to justify the
    rebuild."""
    if q is None:
        return None
    mode = mode or plan_segment_mode()
    if mode == "off" or q.n < MIN_RUN:
        return None
    backend = "np" if mode == "np" else "jax"
    chain_l, chain_r, run_id = _scan_doc(q, backend)
    plan = SegmentPlan()
    plan.chain_l, plan.chain_r, plan.run_id = chain_l, chain_r, run_id
    plan.spans = _chain_spans(q, chain_l, chain_r, run_id)
    plan.hint_l = plan.hint_r = None
    plan.snapshot_reused = False
    if snapshot is None or _needed(q, chain_l, chain_r) <= SNAPSHOT_SKIP_MAX:
        # monotone chained run: the prior per-slot sorted segments are
        # reused as-is by the caller's bisect — no snapshot rebuild
        plan.snapshot_reused = True
        _pc.note_snapshot_reuse()
        return plan
    flat_slot, flat_clock, flat_row, row_len, _n_slots = snapshot()
    q_slot = np.concatenate([q.o_slot, q.r_slot])
    q_ck = np.concatenate([q.o_ck, q.r_ck])
    if backend == "jax":
        fk, qk = kernels._compose_keys(flat_slot, flat_clock, q_slot, q_ck)
        fb = _bucket_pow2(max(1, fk.shape[0]))
        nb = _bucket_pow2(qk.shape[0])
        cand = np.asarray(
            kernels._anchor_lookup_jax(
                _pad_pow2(fk, fb, np.iinfo(np.int64).max),
                _pad_pow2(qk, nb, -1),
            )
        )[: 2 * q.n]
    else:
        cand = kernels.plan_anchor_lookup(
            flat_slot, flat_clock, q_slot, q_ck, backend="np"
        )
    hint = _verify_hints(
        cand, q_slot, q_ck, flat_slot, flat_clock, flat_row, row_len
    )
    plan.hint_l, plan.hint_r = hint[: q.n], hint[q.n :]
    return plan


@functools.lru_cache(maxsize=8)
def _sharded_lookup(mesh, axis: str):
    """Chunk anchor lookup sharded over the doc mesh: the query axis
    splits across devices, the flat snapshot replicates (it is the
    search *haystack* — every shard binary-searches its own query
    block).  Follows the ``sharded_apply_plan`` idiom so the kernel
    profiler attributes retraces/compiles the same way."""
    import jax
    import jax.numpy as jnp

    from ..obs.prof import profiled
    from ..parallel.mesh import P, shard_map

    def local(flat_key, q_key):
        return jnp.searchsorted(flat_key, q_key, side="right") - 1

    sharded = shard_map(
        local, mesh=mesh, in_specs=(P(), P(axis)), out_specs=P(axis)
    )
    return profiled("plan_chunk_anchor_lookup")(jax.jit(sharded))


def plan_chunk(items, mode: str | None = None, mesh=None):
    """Plan a whole flush chunk of cold docs in one batched kernel pass.

    ``items`` is a list of ``(queries, snapshot)`` pairs (either may be
    ``None``); returns a same-length list of :class:`SegmentPlan` (or
    ``None``) per doc.  Doc boundaries break chains via the doc-aware
    conflict scan; anchor lookups for every doc that still needs its
    snapshot compose ``(doc, slot, clock)`` into one key space so a
    single searchsorted — sharded over ``mesh`` when given — resolves
    the entire chunk.
    """
    mode = mode or plan_segment_mode()
    out = [None] * len(items)
    if mode == "off":
        return out
    live = [
        i for i, (q, _s) in enumerate(items)
        if q is not None and q.n >= MIN_RUN
    ]
    if not live:
        return out
    if mode != "device" or len(live) == 1:
        for i in live:
            q, snap = items[i]
            out[i] = plan_doc(q, mode=mode, snapshot=snap)
        return out
    _pc.note_segment_chunk()
    # ---- one conflict scan over the doc-major concatenation ----------
    qs = [items[i][0] for i in live]
    ns = np.array([q.n for q in qs], np.int64)
    doc_id = np.repeat(np.arange(len(qs), dtype=np.int64), ns)
    cat = {
        name: np.concatenate([getattr(q, name) for q in qs])
        for name in ("client", "clock", "length", "o_cl", "o_ck",
                     "r_cl", "r_ck")
    }
    total_q = int(ns.sum())
    nb = _bucket_pow2(total_q)
    l, r, g = kernels._chunk_conflict_scan_jax(
        _pad_pow2(doc_id, nb, -1),
        _pad_pow2(cat["client"], nb, -1),
        _pad_pow2(cat["clock"], nb, 0),
        _pad_pow2(cat["length"], nb, 0),
        _pad_pow2(cat["o_cl"], nb, -1),
        _pad_pow2(cat["o_ck"], nb, 0),
        _pad_pow2(cat["r_cl"], nb, -1),
        _pad_pow2(cat["r_ck"], nb, 0),
    )
    l = np.asarray(l)[:total_q]
    r = np.asarray(r)[:total_q]
    g = np.asarray(g)[:total_q]
    offs = np.concatenate([[0], np.cumsum(ns)])
    for k, i in enumerate(live):
        q = qs[k]
        plan = SegmentPlan()
        sl = slice(int(offs[k]), int(offs[k + 1]))
        plan.chain_l = l[sl].copy()
        plan.chain_r = r[sl].copy()
        plan.run_id = g[sl].copy()
        plan.spans = _chain_spans(q, plan.chain_l, plan.chain_r, plan.run_id)
        plan.hint_l = plan.hint_r = None
        plan.snapshot_reused = False
        out[i] = plan
    # ---- one composed-key lookup for every doc still needing one -----
    lookup = []
    for k, i in enumerate(live):
        q, snap = items[i]
        if snap is None or _needed(q, out[i].chain_l, out[i].chain_r) \
                <= SNAPSHOT_SKIP_MAX:
            out[i].snapshot_reused = True
            _pc.note_snapshot_reuse()
            continue
        lookup.append((k, i, snap()))
    if not lookup:
        return out
    slot_base = 0
    f_parts, qk_parts, meta = [], [], []
    base_clock = 2
    for _k, _i, (fs, fc, _fr, _rl, n_slots) in lookup:
        if fc.shape[0]:
            base_clock = max(base_clock, int(fc.max()) + 2)
    for k, i, (fs, fc, fr, rl, n_slots) in lookup:
        q = qs[k]
        q_slot = np.concatenate([q.o_slot, q.r_slot])
        q_ck = np.concatenate([q.o_ck, q.r_ck])
        base_clock = max(
            base_clock, (int(q_ck.max()) + 2) if q_ck.shape[0] else 2
        )
        f_parts.append((fs + slot_base, fc))
        qk_parts.append(
            (np.where(q_slot >= 0, q_slot + slot_base, -1), q_ck, q_slot)
        )
        meta.append((k, i, fr, rl, q_ck))
        slot_base += n_slots
    flat_gkey = np.concatenate(
        [gs * base_clock + fc for gs, fc in f_parts]
    ) if f_parts else np.empty(0, np.int64)
    q_gkey = np.concatenate(
        [np.where(gs >= 0, gs * base_clock + ck, -1)
         for gs, ck, _ls in qk_parts]
    )
    fb = _bucket_pow2(max(1, flat_gkey.shape[0]))
    qb = _bucket_pow2(q_gkey.shape[0])
    fk_pad = _pad_pow2(flat_gkey, fb, np.iinfo(np.int64).max)
    if mesh is not None and mesh.devices.size > 1:
        axis = mesh.axis_names[0]
        size = int(mesh.shape[axis])
        if qb % size:
            qb = ((qb + size - 1) // size) * size
        qk_pad = _pad_pow2(q_gkey, qb, -1)
        cand_all = np.asarray(_sharded_lookup(mesh, axis)(fk_pad, qk_pad))
    else:
        qk_pad = _pad_pow2(q_gkey, qb, -1)
        cand_all = np.asarray(kernels._anchor_lookup_jax(fk_pad, qk_pad))
    cand_all = cand_all[: q_gkey.shape[0]]
    # ---- split hints back per doc ------------------------------------
    flat_rows = np.concatenate([fr for _k, _i, fr, _rl, _q in meta]) \
        if meta else np.empty(0, np.int64)
    flat_slots_g = np.concatenate([gs for gs, _fc in f_parts]) \
        if f_parts else np.empty(0, np.int64)
    flat_clocks = np.concatenate([fc for _gs, fc in f_parts]) \
        if f_parts else np.empty(0, np.int64)
    qoff = 0
    # per-doc row_len tables differ, so verify per doc over its block
    foff = 0
    for (k, i, fr, rl, _q_ck), (gs_q, q_ck, _ls) in zip(meta, qk_parts):
        q = qs[k]
        nq = 2 * q.n
        nf = fr.shape[0]
        # global candidate -> doc-local index; a query whose key sorts
        # before this doc's flat block lands in a previous doc's region
        # (cand < 0 after the shift) and verifies to NULL
        cand = cand_all[qoff : qoff + nq] - foff
        hint = _verify_hints(
            cand,
            gs_q,
            q_ck,
            flat_slots_g[foff : foff + nf],
            flat_clocks[foff : foff + nf],
            fr,
            rl,
        )
        out[i].hint_l = hint[: q.n]
        out[i].hint_r = hint[q.n :]
        qoff += nq
        foff += nf
    return out
