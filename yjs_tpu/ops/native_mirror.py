"""NativeMirror: DocMirror's interface served by the C++ plan core.

The flush hot path (ingest -> prepare_step -> static_columns) runs entirely
inside yjs_tpu/native/plancore.cpp — the per-item Python interpreter cost
that dominated the distinct-doc benchmark (r2 VERDICT: 11.6ms/doc of plan
building) drops to one ctypes call per flush.  Everything *outside* the hot
path — exports, wire encodes, event payloads — is served by lazily syncing
the C++ columns into a shadow :class:`DocMirror` and delegating to its
(pure-read) methods, so the two implementations cannot drift in behavior:
the shadow IS the reference implementation operating on the same data.

Scope fallbacks keep semantics identical to the Python mirror:
- subdocuments (ContentDoc) raise :class:`UnsupportedUpdate`, demoting the
  doc to the CPU core exactly like the Python path (engine policy seam);
- payloads the native scanner will not carry (legacy ContentJSON inside a
  V2 update) also raise UnsupportedUpdate — the engine's CPU fallback
  serves them;
- malformed updates raise the same decode errors as the Python path
  (re-validated through decode_update_refs so the error type matches).
"""

from __future__ import annotations

import ctypes
import os
import time

import numpy as np

from ..lib0.decoding import Decoder
from ..lib0 import decoding
from ..lib0.u16 import utf8_decode_u16
from ..native import (
    SRC_ANYS,
    SRC_DELETED,
    SRC_FRAMED,
    SRC_JSONS,
    SRC_NONE,
    SRC_UTF8,
    SRC_V2LAZY,
    has_plancore,
    load,
)
from .columns import (
    NULL,
    DocMirror,
    LazyContent,
    LazyContentV2,
    UnsupportedUpdate,
    decode_update_refs,
)
from . import plan_cache as _pc

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_u32p = ctypes.POINTER(ctypes.c_uint32)


def native_plan_available() -> bool:
    # env opt-out first: has_plancore() may trigger the g++ build
    return not os.environ.get("YTPU_NO_NATIVE_PLAN") and has_plancore()


def _sync_plan_segment(lib) -> None:
    """Mirror the YTPU_PLAN_SEGMENT knob into the core's emit_row gate
    so the ``off`` A/B lane also disables the native chain-run anchor
    adoption (ISSUE 15).  No-op on a stale binary-only .so."""
    if lib is None or not getattr(lib, "_has_plan_segment", False):
        return
    from . import segment_planner

    lib.ymx_set_plan_segment(
        0 if segment_planner.plan_segment_mode() == "off" else 1
    )


def plan_segment_stats() -> tuple[int, int]:
    """Cumulative (chain-run adoptions, fragment-search lookups) across
    every native prepare in the process; callers diff around a flush.
    (0, 0) when the core (or the symbol) is unavailable."""
    if not native_plan_available():
        return (0, 0)
    lib = load()
    if lib is None or not getattr(lib, "_has_plan_segment", False):
        return (0, 0)
    out = np.zeros(2, np.int64)
    lib.ymx_plan_segment_stats(_p64(out))
    return (int(out[0]), int(out[1]))


def _p64(a: np.ndarray):
    return a.ctypes.data_as(_i64p)


def _p32(a: np.ndarray):
    return a.ctypes.data_as(_i32p)


class NativePlan:
    """Array-backed step plan (the C++ twin of :class:`StepPlan`).

    ``splits``/``sched``/``sched8``/``delete_rows`` are numpy arrays (use
    ``len()``, not truthiness); ``applied_ds`` is a plain list of tuples
    for the encode path.  ``pack_into`` fills an engine-allocated
    ``[L, W, 8]`` int32 block level-major (vectorized, no per-entry
    Python)."""

    def __init__(self, lib, h, counts, mirror):
        (self.n_rows, n_splits, n_sched, self._n_s8, self.n_levels,
         self.max_width, n_del, self._n_ads) = (int(x) for x in counts[:8])
        n_links, n_heads = int(counts[12]), int(counts[13])
        # full counts row retained for the plan cache (insert after a
        # cold per-doc prepare needs it)
        self.counts = np.array(counts, np.int64, copy=True)
        self._lib, self._h = lib, h
        # staleness guard for lazy sections: the C++ plan buffers are
        # overwritten by the mirror's next prepare
        self._mirror = mirror
        self._seq = mirror._plan_seq
        self._n_sched = n_sched
        # hot-path sections fetched eagerly (the bulk apply + split count)
        self.splits = np.empty((n_splits, 2), np.int64)
        self.delete_rows = np.empty(n_del, np.int64)
        self.link_rows = np.empty(n_links, np.int64)
        self.link_vals = np.empty(n_links, np.int64)
        self.head_segs = np.empty(n_heads, np.int64)
        self.head_vals = np.empty(n_heads, np.int64)
        if n_splits:
            lib.ymx_plan_splits(h, _p64(self.splits))
        if n_del:
            lib.ymx_plan_deletes(h, _p64(self.delete_rows))
        if n_links:
            lib.ymx_plan_links(h, _p64(self.link_rows), _p64(self.link_vals))
        if n_heads:
            lib.ymx_plan_heads(h, _p64(self.head_segs), _p64(self.head_vals))
        self._sched = self._sched8 = self._levels = self._applied = None

    def _fresh(self):
        if self._seq != self._mirror._plan_seq:
            raise RuntimeError(
                "stale NativePlan: the mirror ran another prepare_step"
            )

    @property
    def sched(self):
        if self._sched is None:
            self._fresh()
            self._sched = np.empty((self._n_sched, 4), np.int64)
            if self._n_sched:
                self._lib.ymx_plan_sched(self._h, _p64(self._sched))
        return self._sched

    @property
    def sched8(self):
        if self._sched8 is None:
            self._fresh()
            self._sched8 = np.empty((self._n_s8, 8), np.int64)
            self._levels = np.empty(self._n_s8, np.int64)
            if self._n_s8:
                self._lib.ymx_plan_sched8(
                    self._h, _p64(self._sched8), _p64(self._levels)
                )
        return self._sched8

    @property
    def levels(self):
        self.sched8
        return self._levels

    @property
    def applied_ds(self):
        if self._applied is None:
            self._fresh()
            ads = np.empty((self._n_ads, 3), np.int64)
            if self._n_ads:
                self._lib.ymx_plan_applied_ds(self._h, _p64(ads))
            self._applied = [tuple(row) for row in ads.tolist()]
        return self._applied

    def pack_into(self, block: np.ndarray) -> None:
        if not len(self.sched8):
            return
        lv = self.levels - 1
        idx = np.argsort(lv, kind="stable")
        sorted_lv = lv[idx]
        starts = np.searchsorted(sorted_lv, np.arange(block.shape[0]))
        pos = np.arange(len(idx)) - starts[sorted_lv]
        block[sorted_lv, pos] = self.sched8[idx].astype(block.dtype)

    def packed_levels(self):
        out: list[list[tuple[int, ...]]] = [[] for _ in range(self.n_levels)]
        for entry, lev in zip(self.sched8.tolist(), self.levels.tolist()):
            out[lev - 1].append(tuple(entry))
        return out


def _empty_v2_update() -> bytes:
    """The no-novelty V2 container (feature byte + nine empty streams +
    0-group 0-DS rest) — the V2 analogue of the V1 b"\\x00\\x00"."""
    from ..coding import UpdateEncoderV2
    from ..lib0 import encoding as lib0enc

    e = UpdateEncoderV2()
    lib0enc.write_var_uint(e.rest_encoder, 0)
    lib0enc.write_var_uint(e.rest_encoder, 0)
    return e.to_bytes()


_EMPTY_V2 = _empty_v2_update()


class NativeMirror:
    """Drop-in DocMirror replacement backed by the native plan core."""

    def __init__(self, root_name: str = "text"):
        lib = load()
        if lib is None or not getattr(lib, "_has_plancore", False):
            raise RuntimeError("native plan core unavailable")
        self._lib = lib
        self._h = lib.ymx_new()
        self.root_name = root_name
        self._incoming: list[tuple[bytes, bool]] = []
        # buf id -> (bytes, pinned nparray view) keeping pointers stable
        self._py_bufs: dict[int, tuple[bytes, np.ndarray]] = {}
        self._realized: dict[int, object] = {}
        self._py = DocMirror(root_name)
        # spill/encode paths realize through the descriptor columns
        self._py.realized_content = self.realized_content
        self._synced_gen = -1
        self._plan_seq = 0
        # mirrors counts[8] of the last prepare: lets the engine skip the
        # per-doc ymx_has_pending call when binning flush work
        self._had_pending = False
        # plan-cache digest chain (ISSUE 9): advances on every successful
        # prepare / deterministic compact, poisons on anything else
        self.plan_frontier = _pc.seed_frontier(root_name)
        # extra per-row source columns the shadow DocMirror has no slot for
        self._src_ofs2: list[int] = []
        self._src_end2: list[int] = []
        self._src_count: list[int] = []
        self._src_v2: list[int] = []

    def __del__(self):
        lib = getattr(self, "_lib", None)
        h = getattr(self, "_h", None)
        if lib is not None and h:
            lib.ymx_free(h)

    # -- hot path -----------------------------------------------------------

    def ingest(self, update: bytes, v2: bool = False) -> None:
        self._incoming.append((update, v2))

    def _stage_bufs(self):
        """Register the staged updates with the core; returns
        (staged, buf_ids, v2_flags) with the facade pins recorded."""
        lib, h = self._lib, self._h
        staged = self._incoming
        n_up = len(staged)
        ids = np.empty(max(1, n_up), np.int64)
        v2s = np.empty(max(1, n_up), np.int64)
        for j, (u, v2) in enumerate(staged):
            arr = np.frombuffer(u, np.uint8)
            bid = lib.ymx_add_buf(
                h, arr.ctypes.data_as(_u8p), ctypes.c_uint64(len(u))
            )
            self._py_bufs[int(bid)] = (u, arr)
            ids[j] = bid
            v2s[j] = 1 if v2 else 0
        return staged, ids, v2s

    def plan_key(self, want_levels: bool, want_sched: bool = True):
        """Plan-cache key for the staged work: kind + frontier + staged
        content digest + plan-shape flags (the flags change the cloned
        ``plan`` member, not the integrated state)."""
        return (
            "n",
            self.plan_frontier,
            _pc.staged_digest(self._incoming),
            bool(want_levels),
            bool(want_sched),
        )

    def adopt_cached(self, entry) -> np.ndarray:
        """Replay a cached post-prepare snapshot onto this doc's handle
        instead of planning: one deep state clone, then the same
        bookkeeping a real prepare would do.  ``entry`` is anything with
        ``h`` (source handle), ``counts``, ``pins`` and
        ``frontier_after`` — a cache entry or a just-planned leader
        mirror wrapped by the engine."""
        self._lib.ymx_clone_state(self._h, entry.h)
        self._incoming = []
        self._plan_seq += 1
        self._had_pending = bool(entry.counts[8])
        # the clone's borrowed buffer pointers reference the source's
        # pinned update payloads; share the pins to keep them alive
        self._py_bufs = dict(entry.pins)
        self._realized.clear()
        self._synced_gen = -1  # force a full shadow rebuild on next _sync
        self.plan_frontier = entry.frontier_after
        return np.array(entry.counts, np.int64, copy=True)

    def _finish_prepare(self, rc, staged, ids, counts) -> None:
        """Post-prepare bookkeeping shared by the per-doc and batched
        paths; raises exactly like the old inline prepare_step body."""
        lib, h = self._lib, self._h
        n_up = len(staged)
        self._incoming = []
        self._plan_seq += 1
        self._had_pending = bool(counts[8])
        if rc != 0:
            # the core may have merged a prefix before failing — this
            # state is not a deterministic function of the digest chain,
            # so no other mirror may ever alias it
            self.plan_frontier = _pc.poison_frontier()
            _pc.note_invalidation("plan-error")
        if rc == -9:
            raise UnsupportedUpdate("subdocument (content ref 9)")
        if rc != 0:
            # truly malformed bytes must raise the same error the Python
            # mirror would; anything the Python decoder accepts is a
            # native-scope limitation -> demote like other scope gaps
            try:
                for u, v2 in staged:
                    decode_update_refs(u, v2)
            except Exception:
                # scan-phase failure: nothing merged; unregister the staged
                # buffers so a catch-and-retry loop cannot accumulate pins
                if n_up:
                    first = int(ids[0])
                    lib.ymx_drop_bufs_from(h, first)
                    for j in range(n_up):
                        self._py_bufs.pop(int(ids[j]), None)
                self._incoming = staged
                raise
            raise UnsupportedUpdate(f"native plan: unsupported payload (rc={rc})")
        self._realized.clear()
        self.plan_frontier = _pc.fold(
            self.plan_frontier, b"u", _pc.staged_digest(staged)
        )

    def make_plan(self, counts) -> NativePlan:
        """Wrap the core's current plan (valid until the next prepare)."""
        return NativePlan(self._lib, self._h, counts, self)

    def prepare_step(self, want_levels: bool | None = None) -> NativePlan:
        # default matches DocMirror: compute the full plan (level schedule
        # included); the engine passes want_levels=False on the bulk path
        if want_levels is None:
            want_levels = True
        lib, h = self._lib, self._h
        _sync_plan_segment(lib)
        staged, ids, v2s = self._stage_bufs()
        counts = np.zeros(16, np.int64)
        rc = lib.ymx_prepare(
            h, _p64(ids), _p64(v2s), len(staged), 1 if want_levels else 0,
            _p64(counts),
        )
        self._finish_prepare(rc, staged, ids, counts)
        return NativePlan(lib, h, counts, self)

    def content_gen(self) -> int:
        """Monotonic change counter (the C++ core's ``gen``): bumps on
        every integrated mutation AND at the end of every prepare, so
        delete-only flushes are visible to cached consumers."""
        return int(self._lib.ymx_gen(self._h))

    @property
    def n_rows(self) -> int:
        return int(self._lib.ymx_n_rows(self._h))

    @property
    def n_segs(self) -> int:
        return int(self._lib.ymx_n_segs(self._h))

    def has_pending(self) -> bool:
        return bool(self._lib.ymx_has_pending(self._h))

    def host_nbytes(self) -> int:
        """Rough host bytes this mirror holds (warm-tier accounting,
        ISSUE 7): pinned update payloads + a per-row core estimate."""
        return (
            sum(len(u) for u, _arr in self._py_bufs.values())
            + self.n_rows * 96
            + self.n_segs * 48
        )

    def deleted_ratio(self) -> float:
        """Deleted content length / total inserted length — the tier GC
        trigger (ISSUE 7).  Straight from the core's state/DS exports;
        no shadow sync, no device traffic."""
        lib, h = self._lib, self._h
        ns = int(lib.ymx_n_slots(h))
        if not ns:
            return 0.0
        state = np.empty(ns, np.int64)
        lib.ymx_state(h, _p64(state))
        total = int(state.sum())
        if not total:
            return 0.0
        nds = int(lib.ymx_ds_count(h))
        if not nds:
            return 0.0
        ds_slot = np.empty(nds, np.int64)
        ds_clock = np.empty(nds, np.int64)
        ds_len = np.empty(nds, np.int64)
        lib.ymx_ds(h, _p64(ds_slot), _p64(ds_clock), _p64(ds_len))
        return min(1.0, int(ds_len.sum()) / total)

    def pending_depth(self) -> int:
        return int(self._lib.ymx_pending_depth(self._h))

    def state_vector(self) -> dict[int, int]:
        lib, h = self._lib, self._h
        ns = int(lib.ymx_n_slots(h))
        if ns == 0:
            return {}
        clients = np.empty(ns, np.int64)
        state = np.empty(ns, np.int64)
        lib.ymx_clients(h, _p64(clients))
        lib.ymx_state(h, _p64(state))
        return {
            int(c): int(s) for c, s in zip(clients, state) if s > 0
        }

    def delete_set(self):
        """The doc's derived DeleteSet straight from the core — a cheap
        Snapshot capture (no shadow sync, no device I/O); the DocMirror
        twin is columns.py delete_set()."""
        from ..core import DeleteItem, DeleteSet

        lib, h = self._lib, self._h
        ds = DeleteSet()
        nds = int(lib.ymx_ds_count(h))
        if not nds:
            return ds
        ds_slot = np.empty(nds, np.int64)
        ds_clock = np.empty(nds, np.int64)
        ds_len = np.empty(nds, np.int64)
        lib.ymx_ds(h, _p64(ds_slot), _p64(ds_clock), _p64(ds_len))
        ns = int(lib.ymx_n_slots(h))
        clients = np.empty(max(1, ns), np.int64)
        lib.ymx_clients(h, _p64(clients))
        by_client: dict[int, list[tuple[int, int]]] = {}
        for s, c, ln in zip(
            ds_slot.tolist(), ds_clock.tolist(), ds_len.tolist()
        ):
            by_client.setdefault(int(clients[s]), []).append((c, ln))
        for cl, ranges in by_client.items():
            ds.clients[cl] = [
                DeleteItem(clock, ln)
                for clock, ln in DocMirror._union_ranges(ranges)
            ]
        return ds

    def static_columns(self, start: int = 0) -> dict[str, np.ndarray]:
        lib, h = self._lib, self._h
        n = self.n_rows - start
        client_key = np.empty(n, np.uint32)
        cols = {k: np.empty(n, np.int32) for k in
                ("origin_slot", "origin_clock", "right_slot", "right_clock",
                 "origin_row")}
        lib.ymx_static_cols(
            h, start, client_key.ctypes.data_as(_u32p),
            _p32(cols["origin_slot"]), _p32(cols["origin_clock"]),
            _p32(cols["right_slot"]), _p32(cols["right_clock"]),
            _p32(cols["origin_row"]),
        )
        return {"client_key": client_key, **cols}

    # -- compaction ---------------------------------------------------------

    def rebuild_compacted_self(self, gc: bool):
        """Compact from the mirror's own list state — no device read-back
        (the flush invariant keeps mirror links == device links).  On a
        stale binary-only .so without ymx_compact_self, the same inputs
        are synthesized host-side from the core's link/head exports and
        fed to the original ymx_compact — still zero device traffic."""
        lib, h = self._lib, self._h
        n = self.n_rows
        nseg = self.n_segs
        new_right = np.full(max(1, n), NULL, np.int32)
        new_del = np.zeros(max(1, n), np.uint8)
        new_heads = np.full(max(1, nseg), NULL, np.int32)
        if getattr(lib, "_has_compact_self", False):
            n_new = lib.ymx_compact_self(
                h, int(bool(gc)), _p32(new_right),
                new_del.ctypes.data_as(_u8p), _p32(new_heads),
                len(new_heads),
            )
            self._realized.clear()
            # compaction-from-self is a pure function of state already in
            # the chain: a deterministic fold, so two docs compacted at
            # the same point keep aliasing each other's cache entries
            self.plan_frontier = _pc.fold(
                self.plan_frontier, b"compact-self", b"g" if gc else b"-"
            )
            _pc.note_invalidation("compact")
            return (
                new_right[:n_new],
                new_del[:n_new].astype(bool),
                new_heads,
            )
        links = np.full(max(1, n), NULL, np.int64)
        if n:
            lib.ymx_links(h, _p64(links))
        heads = np.full(max(1, nseg), NULL, np.int64)
        if nseg:
            lib.ymx_heads(h, _p64(heads))
        deleted = np.zeros(max(1, n), bool)
        for r in self._host_deleted_rows:
            deleted[r] = True
        return self.rebuild_compacted(
            links.astype(np.int32), deleted, heads.astype(np.int32), gc
        )

    def rebuild_compacted(self, right_link, deleted, head_of_seg, gc: bool):
        lib, h = self._lib, self._h
        n = self.n_rows
        nseg = self.n_segs
        right = np.ascontiguousarray(np.asarray(right_link)[: max(1, n)],
                                     np.int32)
        dele = np.ascontiguousarray(
            np.asarray(deleted)[: max(1, n)].astype(np.uint8)
        )
        heads = np.ascontiguousarray(np.asarray(head_of_seg), np.int32)
        new_right = np.full(max(1, n), NULL, np.int32)
        new_del = np.zeros(max(1, n), np.uint8)
        new_heads = np.full(max(1, nseg), NULL, np.int32)
        n_new = lib.ymx_compact(
            h, _p32(right), dele.ctypes.data_as(_u8p), _p32(heads),
            len(heads), int(bool(gc)), _p32(new_right),
            new_del.ctypes.data_as(_u8p), _p32(new_heads), len(new_heads),
        )
        self._realized.clear()
        # link/deleted/head inputs come from the caller, so fold their
        # content in: same inputs -> same chain, anything else diverges
        self.plan_frontier = _pc.fold(
            self.plan_frontier,
            b"compact",
            right.tobytes() + dele.tobytes() + heads.tobytes()
            + (b"g" if gc else b"-"),
        )
        _pc.note_invalidation("compact")
        return (
            new_right[:n_new],
            new_del[:n_new].astype(bool),
            new_heads,
        )

    # -- native wire encodes -------------------------------------------------

    def encode_diff_update(
        self, target_sv: dict[int, int] | None, ds_ranges=None,
        v2: bool = False,
    ) -> bytes | None:
        """The doc's diff against ``target_sv`` encoded fully natively
        (reference encodeStateAsUpdate, encoding.js:490-526); ``ds_ranges``
        overrides the DS section (the flush-novelty form); ``v2`` selects
        the 9-stream columnar container.  Returns None when the native
        writer cannot serve the selection — for V1 output that is V2-framed
        embed/format/type payloads, for V2 output V1-framed ones, plus any
        Python-realized (spilled) content — and callers fall back to the
        shadow's encode."""
        lib, h = self._lib, self._h
        sv = target_sv or {}
        n_sv = len(sv)
        svc = np.fromiter(sv.keys(), np.int64, n_sv) if n_sv else np.zeros(1, np.int64)
        svk = np.fromiter(sv.values(), np.int64, n_sv) if n_sv else np.zeros(1, np.int64)
        if ds_ranges is None:
            ds = np.zeros(3, np.int64)
            n_ds, override = 0, 0
        else:
            n_ds = len(ds_ranges)
            ds = (
                np.asarray(ds_ranges, np.int64).reshape(-1)
                if n_ds
                else np.zeros(3, np.int64)
            )
            override = 1
        fn = lib.ymx_encode_diff_v2 if v2 else lib.ymx_encode_diff
        cap = int(lib.ymx_encode_bound(h))
        for _attempt in range(2):
            out = np.empty(cap, np.uint8)
            rc = int(
                fn(
                    h, _p64(svc), _p64(svk), n_sv, _p64(ds), n_ds,
                    override, out.ctypes.data_as(_u8p),
                    ctypes.c_uint64(len(out)),
                )
            )
            if rc < -100:  # overflow: the V2 writer reports the exact
                # size needed (the bound is V1-derived) — retry once with
                # an exact buffer rather than degrading to Python
                cap = -rc
                continue
            if rc < 0:
                return None
            return out[:rc].tobytes()
        return None

    def encode_state_as_update(self, target_sv=None, v2: bool = False) -> bytes:
        u = self.encode_diff_update(target_sv, v2=v2)
        if u is not None:
            return u
        self._sync()
        return DocMirror.encode_state_as_update(self._py, target_sv, v2=v2)

    def encode_step_update(self, pre_sv, plan, v2: bool = False) -> bytes | None:
        u = self.encode_diff_update(pre_sv, ds_ranges=plan.applied_ds, v2=v2)
        if u is not None:
            # a no-novelty update means the flush changed nothing visible —
            # match the None contract (V1: 0 groups + 0 DS clients; the V2
            # container's empty form is longer, compare against it)
            if not v2 and u == b"\x00\x00":
                return None
            if v2 and u == _EMPTY_V2:
                return None
            return u
        self._sync()
        return DocMirror.encode_step_update(self._py, pre_sv, plan, v2=v2)

    # -- content realization -------------------------------------------------

    def realized_content(self, row: int):
        c = self._realized.get(row)
        if c is not None:
            return c
        self._sync()
        py = self._py
        kind = py.row_src_kind[row]
        ref = py.row_content_ref[row]
        if kind == SRC_NONE:
            return None
        buf = py._bufs[py.row_src_buf[row]] if py.row_src_buf[row] >= 0 else b""
        ofs, end = py.row_src_ofs[row], py.row_src_end[row]
        if kind == SRC_DELETED:
            from ..core import ContentDeleted

            c = ContentDeleted(py.row_len[row])
        elif kind == SRC_UTF8:
            from ..core import ContentString

            c = ContentString(utf8_decode_u16(buf[ofs:end]))
        elif kind == SRC_FRAMED:
            c = LazyContent(buf, ofs, ref, end).realize()
        elif kind in (SRC_ANYS, SRC_JSONS):
            # synthesize the V1 framing (varuint count + elements) and use
            # the reference read path so element semantics cannot drift
            from ..lib0 import encoding as lib0enc

            enc = lib0enc.Encoder()
            lib0enc.write_var_uint(enc, self._src_count[row])
            synth = enc.to_bytes() + buf[ofs:end]
            c = LazyContent(synth, 0, ref, len(synth)).realize()
        elif kind == SRC_V2LAZY:
            c = LazyContentV2(
                buf, ref, ofs, end,
                self._src_ofs2[row], self._src_end2[row],
                self._src_count[row],
            ).realize()
        else:  # SRC_SPILL never originates here
            raise AssertionError(f"unexpected src kind {kind}")
        self._realized[row] = c
        return c

    # -- shadow sync + delegation -------------------------------------------

    def _sync(self) -> None:
        lib, h = self._lib, self._h
        gen = int(lib.ymx_gen(h))
        if gen == self._synced_gen:
            return
        py = self._py
        n = self.n_rows
        cols = {k: np.empty(n, np.int64) for k in (
            "slot", "clock", "len", "oslot", "oclock", "rslot", "rclock",
            "is_gc", "countable", "ref", "seg", "src_kind", "src_buf",
            "src_ofs", "src_end", "src_ofs2", "src_end2", "src_count",
            "src_v2", "host_deleted", "lww_deleted",
        )}
        if n:
            lib.ymx_rows(h, 0, *(_p64(cols[k]) for k in cols))
        # numpy-backed shadow columns: the fetch is pure memcpy (no per-row
        # Python boxing), and every DocMirror read path accepts sequence
        # indexing — a 100k-row sync is a few MB of memcpy, not 2M tolist()
        # boxings (r3 review finding)
        py.row_slot = cols["slot"]
        py.row_clock = cols["clock"]
        py.row_len = cols["len"]
        py.row_origin_slot = cols["oslot"]
        py.row_origin_clock = cols["oclock"]
        py.row_right_slot = cols["rslot"]
        py.row_right_clock = cols["rclock"]
        py.row_is_gc = cols["is_gc"]
        py.row_countable = cols["countable"]
        py.row_content = [None] * n
        py.row_content_ref = cols["ref"]
        py.row_seg = cols["seg"]
        py.row_src_kind = cols["src_kind"]
        py.row_src_buf = cols["src_buf"]
        py.row_src_ofs = cols["src_ofs"]
        py.row_src_end = cols["src_end"]
        self._src_ofs2 = cols["src_ofs2"]
        self._src_end2 = cols["src_end2"]
        self._src_count = cols["src_count"]
        self._src_v2 = cols["src_v2"]
        py._host_deleted_rows = set(
            np.flatnonzero(cols["host_deleted"]).tolist()
        )
        py._lww_deleted = set(np.flatnonzero(cols["lww_deleted"]).tolist())

        ns = int(lib.ymx_n_slots(h))
        clients = np.empty(max(1, ns), np.int64)
        state = np.empty(max(1, ns), np.int64)
        if ns:
            lib.ymx_clients(h, _p64(clients))
            lib.ymx_state(h, _p64(state))
        py.client_of_slot = clients[:ns].tolist()
        py.slot_of_client = {c: i for i, c in enumerate(py.client_of_slot)}
        py.state = state[:ns].tolist()
        # host list state (the device right_link/starts mirror)
        links = np.empty(max(1, n), np.int64)
        if n:
            lib.ymx_links(h, _p64(links))
        py.list_next = links[:n]
        # fragment index: straight memcpy of the C++ index (already sorted)
        counts = np.zeros(max(1, ns), np.int64)
        if ns:
            lib.ymx_frag_counts(h, _p64(counts))
        py.frag_clock = []
        py.frag_row = []
        for s in range(ns):
            k = int(counts[s])
            fc = np.empty(max(1, k), np.int64)
            fr = np.empty(max(1, k), np.int64)
            if k:
                lib.ymx_frag(h, s, _p64(fc), _p64(fr))
            py.frag_clock.append(fc[:k])
            py.frag_row.append(fr[:k])

        # segments + interned strings
        nseg = self.n_segs
        blob_len = int(lib.ymx_strings_len(h))
        blob = np.empty(max(1, blob_len), np.uint8)
        if blob_len:
            lib.ymx_strings(h, blob.ctypes.data_as(_u8p))
        py._strings = bytearray(blob[:blob_len].tobytes())
        segc = {k: np.empty(max(1, nseg), np.int64) for k in
                ("name_ofs", "name_len", "sub_ofs", "sub_len", "parent")}
        if nseg:
            lib.ymx_segs(h, *(_p64(segc[k]) for k in segc))
        heads = np.empty(max(1, nseg), np.int64)
        if nseg:
            lib.ymx_heads(h, _p64(heads))
        py.head_of_seg = heads[:nseg]
        py.seg_name_ofs = segc["name_ofs"][:nseg].tolist()
        py.seg_name_len = segc["name_len"][:nseg].tolist()
        py.seg_sub_ofs = segc["sub_ofs"][:nseg].tolist()
        py.seg_sub_len = segc["sub_len"][:nseg].tolist()
        sb = bytes(py._strings)
        seg_info = []
        for i in range(nseg):
            no, nl = py.seg_name_ofs[i], py.seg_name_len[i]
            so, sl = py.seg_sub_ofs[i], py.seg_sub_len[i]
            name = utf8_decode_u16(sb[no : no + nl]) if no >= 0 else None
            sub = utf8_decode_u16(sb[so : so + sl]) if so >= 0 else None
            seg_info.append((name, sub, int(segc["parent"][i])))
        py.seg_info = seg_info
        py.segments = {key: i for i, key in enumerate(seg_info)}
        py._segs_of_parent = {}
        for i, (_n, _s, p) in enumerate(seg_info):
            if p != NULL:
                py._segs_of_parent.setdefault(p, []).append(i)
        py.map_chain = {}
        for i, (_n, sub, _p) in enumerate(seg_info):
            if sub is None:
                continue
            cl = int(lib.ymx_chain_len(h, i))
            if cl:
                chain = np.empty(cl, np.int64)
                lib.ymx_chain(h, i, _p64(chain))
                py.map_chain[i] = chain.tolist()

        # delete-set ranges in slot first-note order
        nds = int(lib.ymx_ds_count(h))
        ds_slot = np.empty(max(1, nds), np.int64)
        ds_clock = np.empty(max(1, nds), np.int64)
        ds_len = np.empty(max(1, nds), np.int64)
        if nds:
            lib.ymx_ds(h, _p64(ds_slot), _p64(ds_clock), _p64(ds_len))
        py.ds = {}
        for s, c, ln in zip(
            ds_slot[:nds].tolist(), ds_clock[:nds].tolist(),
            ds_len[:nds].tolist()
        ):
            py.ds.setdefault(s, []).append((c, ln))

        # buffer table: Python-origin bytes + arena chunks fetched once
        nb = int(lib.ymx_n_bufs(h))
        bufs: list[bytes] = []
        for i in range(nb):
            known = self._py_bufs.get(i)
            if known is not None:
                bufs.append(known[0])
            else:
                ln = int(lib.ymx_buf_len(h, i))
                chunk = np.empty(max(1, ln), np.uint8)
                if ln:
                    lib.ymx_copy_bytes(
                        h, i, 0, ln, chunk.ctypes.data_as(_u8p)
                    )
                b = chunk[:ln].tobytes()
                self._py_bufs[i] = (b, chunk)
                bufs.append(b)
        py._bufs = bufs

        py._gen = gen
        py._np_gen = -1
        py._ds_gen = gen
        py._ds_np_gen = -1
        self._synced_gen = gen

    def __getattr__(self, name):
        if name.startswith("__") or "_py" not in self.__dict__:
            raise AttributeError(name)
        self._sync()
        return getattr(self.__dict__["_py"], name)


def prepare_many(work, want_levels: bool = False, want_sched: bool = True,
                 obs=None):
    """Batched ymx_prepare over many NativeMirrors in ONE native call.

    ``work`` is a list of ``(doc_idx, NativeMirror)``.  Returns
    ``(counts, rcs, staged_info)`` where ``counts`` is an ``(n, 16)``
    int64 array (ymx_prepare layout + ``[14]`` = dense-link flag),
    ``rcs`` the per-doc return codes, and ``staged_info`` the
    per-doc ``(staged, ids)`` needed by ``_finish_prepare``.

    ``obs`` (an :class:`yjs_tpu.obs.EngineObs`) records each call's wall
    time and doc count into the ``ytpu_native_prepare_many_*`` histograms
    — the planner-pool visibility the engine's per-flush timers cannot
    give once flushes span multiple chunks.

    ``want_sched=False`` skips building each plan's sched section
    (``NativePlan.sched`` then reads back empty) — ONLY safe when no
    consumer will read it, e.g. the bulk-apply flush with no event
    listeners; ``ymx_prepare``/``prepare_step`` always build it.

    Replaces the per-doc ctypes round trip that made the host planner
    72% of distinct-doc flush time (BENCH_r03 host_phase_timers).
    """
    t0 = time.perf_counter()
    n = len(work)
    lib = work[0][1]._lib
    _sync_plan_segment(lib)
    handles = (ctypes.c_void_p * n)()
    buf_ofs = np.zeros(n + 1, np.int64)
    if getattr(lib, "_has_add_bufs_many", False):
        # batched staging: ONE native call registers every staged buffer.
        # The c_char_p array extracts each bytes object's pointer in C
        # (no per-buffer numpy view); the bytes stay pinned via _py_bufs.
        all_bytes: list[bytes] = []
        v2_list: list[int] = []
        buf_hs = []
        for k, (_i, m) in enumerate(work):
            staged = m._incoming
            buf_ofs[k + 1] = buf_ofs[k] + len(staged)
            for u, v2 in staged:
                all_bytes.append(u)
                v2_list.append(1 if v2 else 0)
                buf_hs.append(m._h)
            handles[k] = m._h
        nb_tot = len(all_bytes)
        ids_flat = np.zeros(max(1, nb_tot), np.int64)
        v2_flat = np.asarray(v2_list or [0], np.int64)
        if nb_tot:
            ptrs = (ctypes.c_char_p * nb_tot)(*all_bytes)
            lens = np.fromiter(
                (len(u) for u in all_bytes), np.uint64, nb_tot
            )
            bhs = (ctypes.c_void_p * nb_tot)(*buf_hs)
            lib.ymx_add_bufs_many(
                bhs, ptrs,
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                nb_tot,
                _p64(ids_flat),
            )
        staged_info = []
        o = 0
        for k, (_i, m) in enumerate(work):
            staged = m._incoming
            nb = len(staged)
            ids = ids_flat[o : o + nb]
            for j, (u, _v2) in enumerate(staged):
                m._py_bufs[int(ids[j])] = (u, None)
            staged_info.append((staged, np.asarray(ids, np.int64)))
            o += nb
    else:  # stale binary-only .so: per-doc staging
        ids_parts, v2_parts, staged_info = [], [], []
        for k, (_i, m) in enumerate(work):
            staged, ids, v2s = m._stage_bufs()
            nb = len(staged)
            staged_info.append((staged, ids))
            buf_ofs[k + 1] = buf_ofs[k] + nb
            if nb:
                ids_parts.append(ids[:nb])
                v2_parts.append(v2s[:nb])
            handles[k] = m._h
        ids_flat = (
            np.concatenate(ids_parts) if ids_parts else np.zeros(1, np.int64)
        )
        v2_flat = (
            np.concatenate(v2_parts) if v2_parts else np.zeros(1, np.int64)
        )
    counts = np.zeros((n, 16), np.int64)
    rcs = np.zeros(n, np.int64)
    lib.ymx_prepare_many(
        handles, n, _p64(buf_ofs), _p64(ids_flat), _p64(v2_flat),
        1 if want_levels else 0, 1 if want_sched else 0, _p64(counts),
        _p64(rcs),
    )
    dt = time.perf_counter() - t0
    if obs is not None:
        obs.native_prepare(n, dt)
    from ..obs.prof import kernel_profiler

    kernel_profiler().record_host_op("prepare_many", dt)
    return counts, rcs, staged_info


def pack_apply_lanes(work, doc_ids, b_loc, n_shards, widths, oob_r, oob_s,
                     null_val, dtype=np.int32, out=None):
    """Fill the bulk-apply scatter lanes for ``work`` (post-prepare
    ``(doc_idx, NativeMirror)`` entries, rc==0) natively.  Returns
    ``(lanes, stats)`` with ``lanes`` shaped ``(n_shards, lane_w)`` and
    ``stats = [n_dense, n_sparse, n_heads, n_dels]`` real elements —
    the native twin of BatchEngine._flush_apply's pack loop.

    ``dtype=np.int16`` halves the transfer when every row/seg index fits
    16 bits (the caller checks capacity); the kernel widens on device.
    ``out`` reuses a caller-owned ``(n_shards, lane_w)`` staging buffer
    (the flush pipeline's double-buffered pair) instead of allocating."""
    k_dn, k_sp, k_h, k_d = widths
    n = len(work)
    lib = work[0][1]._lib
    handles = (ctypes.c_void_p * n)()
    for k, (_i, m, *_rest) in enumerate(work):
        handles[k] = m._h
    lane_w = 4 * b_loc + k_dn + 2 * k_sp + 2 * k_h + k_d
    if out is not None and out.shape == (n_shards, lane_w) and out.dtype == dtype:
        lanes = out
    else:
        lanes = np.empty((n_shards, lane_w), dtype)
    stats = np.zeros(4, np.int64)
    ids = np.ascontiguousarray(doc_ids, np.int64)
    fn = lib.ymx_pack_apply16 if dtype == np.int16 else lib.ymx_pack_apply
    fn(
        handles, _p64(ids), n, b_loc, n_shards, k_dn, k_sp, k_h, k_d,
        ctypes.c_int32(oob_r), ctypes.c_int32(oob_s),
        ctypes.c_int32(null_val),
        lanes.ctypes.data_as(ctypes.c_void_p), _p64(stats),
    )
    return lanes, stats
