"""YEvent-shaped change sets computed from a flush's step plan.

The reference delivers per-type events with ``changes = {delta, keys}``
after every transaction (reference YEvent.js:85-187, callObservers
AbstractType.js:360-389).  An engine-hosted doc has no Item graph to walk,
but the planner's host state is sufficient: add/delete classification is
clock-based exactly like the reference (``adds`` = clock >= beforeState,
``deletes`` = covered by the transaction's DeleteSet — here the flush's
``applied_ds``), the list walk follows the host ``list_next`` links, and
map key changes come from the per-key chains.  Semantics mirror the base
``YEvent.changes`` computation in yjs_tpu/types/events.py line for line so
the engine's payloads equal the CPU doc's on the same traffic (the parity
tests in tests/test_engine_events.py).
"""

from __future__ import annotations

from bisect import bisect_right

from ..core import UNDEFINED
from .columns import NULL


def _coverage(applied_ds):
    """Per-client sorted delete ranges of this flush (the transaction's
    DeleteSet, reference isDeleted DeleteSet.js:75-105)."""
    by_client: dict[int, list[tuple[int, int]]] = {}
    for client, clock, ln in applied_ds:
        by_client.setdefault(client, []).append((clock, clock + ln))
    for ranges in by_client.values():
        ranges.sort()
    return by_client


def _deleted_now(cov, client, clock) -> bool:
    ranges = cov.get(client)
    if not ranges:
        return False
    i = bisect_right(ranges, (clock, float("inf"))) - 1
    return i >= 0 and ranges[i][0] <= clock < ranges[i][1]


def compute_flush_events(mirror, plan, pre_state: dict[int, int]):
    """Events for one flush: list of ``{"path", "delta", "keys"}`` dicts,
    one per changed type, shaped like the CPU path's ``YEvent``.

    ``pre_state`` is the doc's state vector before the flush (the
    reference transaction's beforeState).
    """
    seg_info = mirror.seg_info
    row_seg = mirror.row_seg
    row_clock = mirror.row_clock
    row_len = mirror.row_len
    row_slot = mirror.row_slot
    client_of_slot = mirror.client_of_slot
    list_next = mirror.list_next
    head_of_seg = mirror.head_of_seg
    host_deleted = mirror._host_deleted_rows

    cov = _coverage(plan.applied_ds)

    def client_of(row):
        return client_of_slot[row_slot[row]]

    def adds(row) -> bool:
        return row_clock[row] >= pre_state.get(client_of(row), 0)

    def deletes(row) -> bool:
        return _deleted_now(cov, client_of(row), row_clock[row])

    def type_recorded(parent) -> bool:
        # the reference only records changed types that existed before the
        # transaction and are alive (addChangedTypeToTransaction,
        # Transaction.js:154-159): a type created this flush is reported
        # by its PARENT's event, not its own
        if parent == NULL:
            return True
        p = int(parent)
        return not adds(p) and p not in host_deleted

    # changed types: group touched segments by (name, parent_row) — the
    # reference fires one event per type with all its keys
    touched: dict[tuple, set] = {}  # type key -> set of parent_subs (None = list)
    rows_touched = [int(r) for r in plan.sched[:, 0]] if hasattr(
        plan.sched, "shape"
    ) else [s[0] for s in plan.sched]
    for r in rows_touched:
        sg = row_seg[r]
        if sg == NULL:
            continue
        name, sub, parent = seg_info[sg]
        if not type_recorded(parent):
            continue
        touched.setdefault((name, parent), set()).add(sub)
    for r in plan.delete_rows:
        r = int(r)
        sg = row_seg[r]
        if sg == NULL:
            continue
        name, sub, parent = seg_info[sg]
        # fragments of rows deleted in EARLIER flushes ride in delete_rows
        # (device bookkeeping) but are not part of this transaction's
        # DeleteSet — the reference would not fire for them
        if sub is None and not deletes(r):
            continue
        if not type_recorded(parent):
            continue
        touched.setdefault((name, parent), set()).add(sub)

    events = []
    for (name, parent), subs in touched.items():
        delta: list = []
        keys: dict = {}
        list_seg = mirror.segments.get((name, None, parent))
        if None in subs and list_seg is not None:
            # base YEvent.changes list walk (types/events.py:45-71)
            last_op = None

            def pack_op(op):
                if op is not None:
                    delta.append(op)

            r = head_of_seg[list_seg]
            while r != NULL:
                r = int(r)
                if r in host_deleted:
                    if deletes(r) and not adds(r):
                        if last_op is None or "delete" not in last_op:
                            pack_op(last_op)
                            last_op = {"delete": 0}
                        last_op["delete"] += int(row_len[r])
                else:
                    if adds(r):
                        if last_op is None or "insert" not in last_op:
                            pack_op(last_op)
                            last_op = {"insert": []}
                        content = mirror.realized_content(r)
                        last_op["insert"] = last_op["insert"] + (
                            content.get_content() if content is not None else []
                        )
                    else:
                        if last_op is None or "retain" not in last_op:
                            pack_op(last_op)
                            last_op = {"retain": 0}
                        last_op["retain"] += int(row_len[r])
                r = list_next[r]
            if last_op is not None and "retain" not in last_op:
                pack_op(last_op)
        for sub in subs:
            if sub is None:
                continue
            seg = mirror.segments.get((name, sub, parent))
            chain = mirror.map_chain.get(seg) if seg is not None else None
            if not chain:
                continue
            # reference key logic (types/events.py:73-101): classify the
            # chain tail against beforeState, old value from the last
            # pre-existing entry
            tail = int(chain[-1])
            if adds(tail):
                j = len(chain) - 2
                while j >= 0 and adds(int(chain[j])):
                    j -= 1
                prev = int(chain[j]) if j >= 0 else None
                if deletes(tail):
                    if prev is not None and deletes(prev):
                        action = "delete"
                        old = mirror.realized_content(prev).get_content()[-1]
                    else:
                        continue
                else:
                    if prev is not None and deletes(prev):
                        action = "update"
                        old = mirror.realized_content(prev).get_content()[-1]
                    else:
                        action = "add"
                        old = UNDEFINED
            else:
                if deletes(tail):
                    action = "delete"
                    old = mirror.realized_content(tail).get_content()[-1]
                else:
                    continue
            keys[sub] = {"action": action, "oldValue": old}
        if not delta and not keys:
            continue
        events.append({
            "path": _path_of(mirror, name, parent),
            "delta": delta,
            "keys": keys,
        })
    return events


# content refs whose CPU classes merge (Item.mergeWith succeeds:
# ContentDeleted/JSON/String/Any — core.py merge_with returns True)
_MERGEABLE_REFS = frozenset((1, 2, 4, 8))


def _rows_one_cpu_item(mirror, p: int, r: int) -> bool:
    """True when list-adjacent mirror rows p,r are ONE Item in the CPU
    store — the exact Item.mergeWith predicate (core.py:862-884 /
    reference Item.js:555-579) evaluated over columns: same client,
    consecutive clocks, r's origin = p's last id, equal right origins,
    equal deleted state, mergeable equal content kinds.  The CPU doc
    merges every such adjacent pair during transaction cleanup, while
    the mirror keeps rows split until compaction — this predicate is
    what keeps the two path indexings identical."""
    if int(mirror.row_slot[p]) != int(mirror.row_slot[r]):
        return False
    if int(mirror.row_clock[p]) + int(mirror.row_len[p]) != int(
        mirror.row_clock[r]
    ):
        return False
    ref = int(mirror.row_content_ref[r])
    if ref != int(mirror.row_content_ref[p]) or ref not in _MERGEABLE_REFS:
        return False
    # r.origin == p.last_id
    if (
        int(mirror.row_origin_slot[r]) != int(mirror.row_slot[p])
        or int(mirror.row_origin_clock[r])
        != int(mirror.row_clock[p]) + int(mirror.row_len[p]) - 1
    ):
        return False
    # equal right origins
    rs_p, rs_r = int(mirror.row_right_slot[p]), int(mirror.row_right_slot[r])
    if rs_p != rs_r:
        return False
    if rs_p != NULL and int(mirror.row_right_clock[p]) != int(
        mirror.row_right_clock[r]
    ):
        return False
    host_deleted = mirror._host_deleted_rows
    return (p in host_deleted) == (r in host_deleted)


def _path_of(mirror, name, parent_row) -> list:
    """Root-to-type path: map keys as strings, list positions counted
    exactly like the reference's getPathTo (YEvent.js:207-228): one per
    undeleted ITEM before the target.  The mirror keeps runs split that
    the CPU store has merged (cleanup merges eagerly, the mirror only at
    compaction), so consecutive rows forming one CPU item
    (_rows_one_cpu_item) count once — pinned against the CPU path by
    tests/test_engine_events.py::test_event_path_parity_*."""
    path: list = []
    host_deleted = mirror._host_deleted_rows
    while parent_row != NULL:
        r = int(parent_row)
        sg = mirror.row_seg[r]
        pname, psub, pparent = mirror.seg_info[sg]
        if psub is not None:
            path.insert(0, psub)
        else:
            i = 0
            c = mirror.head_of_seg[sg]
            prev = None  # previous row in LIST order (deleted included:
            # a deleted run between two live runs breaks CPU adjacency)
            while c != NULL and int(c) != r:
                c = int(c)
                if c not in host_deleted and not (
                    prev is not None and _rows_one_cpu_item(mirror, prev, c)
                ):
                    i += 1
                prev = c
                c = mirror.list_next[c]
            path.insert(0, i)
        name, parent_row = pname, pparent
    path.insert(0, name)
    return path
