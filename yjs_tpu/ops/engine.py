"""BatchEngine: batched TPU apply_update over many docs.

The device-side half of the `y-tpu` Provider described in BASELINE.json's
north star: pending binary updates from many docs are marshalled into
struct-of-arrays columns (:mod:`.columns`), integrated by the vmapped YATA
kernel (:mod:`.kernels`), and the persistent device state (links, segment
heads, deleted bits) lives across flushes.  Root text/list/map types and
arbitrarily nested shared types are all served on device (nested types are
parent-row-keyed segments, reference ContentType.js); only docs embedding
subdocuments transparently fall back to the CPU reference core — the
Provider gating seam.
"""

from __future__ import annotations

import contextlib
import os
import time
from types import SimpleNamespace

import numpy as np

try:  # pragma: no cover - exercised implicitly on import
    import jax
    import jax.numpy as jnp

    HAS_JAX = True
except Exception:  # pragma: no cover
    HAS_JAX = False

from ..core import Doc
from ..lib0.u16 import from_u16
from ..obs import EngineObs, new_flush_metrics
from ..obs.prof import profiled
from ..resilience import DeadLetterQueue, HealthTracker
from ..updates import InvalidUpdate, validate_update
from ..updates import apply_update, apply_update_v2
from .columns import NULL, DocMirror, UnsupportedUpdate
from . import plan_cache
from . import segment_planner
from .native_mirror import (
    NativeMirror,
    native_plan_available,
    pack_apply_lanes,
    plan_segment_stats,
    prepare_many,
)
from . import kernels


def _native_plan_threads() -> int:
    """Worker-pool width ymx_prepare_many fans out to (1 when the native
    planner is unavailable or the host has a single core)."""
    try:
        from ..native import has_plancore, load

        lib = load()
        if (
            lib is not None
            and has_plancore()
            and getattr(lib, "_has_plan_threads", False)
        ):
            return int(lib.ymx_plan_threads())
    except Exception:
        pass
    return 1


def make_mirror(root_name: str):
    """DocMirror served by the C++ plan core when available; the pure-
    Python mirror otherwise (no toolchain / YTPU_NO_NATIVE_PLAN)."""
    if native_plan_available():
        return NativeMirror(root_name)
    return DocMirror(root_name)


def visible_text(mirror, rows, deleted) -> str:
    """Materialize visible text from document-ordered rows + deleted flags.

    Content strings are UTF-16 code units (surrogate pairs may be split
    across runs, reference ContentString.js:51-66); recombine like
    YText.to_string does.  Shared by BatchEngine.text and bench.py.
    """
    out = []
    for r, d in zip(rows, deleted):
        if d or not mirror.row_countable[r]:
            continue
        content = mirror.realized_content(r)
        s = getattr(content, "str", None)
        if s is not None:
            out.append(s)
        else:
            out.append("".join(str(x) for x in getattr(content, "arr", [])))
    return from_u16("".join(out))


def _bucket(n: int, minimum: int = 64) -> int:
    """Round up to the padding bucket (power of two) to bound recompiles."""
    b = minimum
    while b < n:
        b *= 2
    return b


# scatter-lane width quantization: 2**bits mantissa steps per power-of-two
# octave.  bits=3 (default) caps padding waste at 12.5% of the request
# (vs 50% for pure powers of two) while keeping the distinct compiled
# shapes bounded at 8 per octave — the measured-distribution bucketing of
# VERDICT r4 item 9.  bits=0 restores pure powers of two.
_PAD_BITS = max(0, min(6, int(os.environ.get("YTPU_PAD_BITS", "3"))))


def _bucket_lanes(n: int, minimum: int = 64) -> int:
    """Round a per-flush LANE width up to the next mantissa-quantized
    bucket.  Used only for transfer-lane widths (the occupancy metric);
    device STATE capacities keep plain powers of two, where fewer, larger
    growth steps amortize the on-device copy better."""
    if n <= minimum:
        return minimum
    bits = _PAD_BITS
    if bits == 0:
        return _bucket(n, minimum)
    e = max(0, (n - 1).bit_length() - 1 - bits)
    return ((n + (1 << e) - 1) >> e) << e


# target size of one level-axis schedule tile (entries per doc-batch block);
# big enough that kernel launch overhead amortizes, small enough that the
# padded [B, block, W, 8] tile stays modest at any log length
_BLOCK_BUDGET = 1 << 22


def _block_levels(n_docs: int, w_lv: int) -> int:
    return _bucket(max(1, _BLOCK_BUDGET // max(1, n_docs * w_lv)), 1)


# resident immutable device columns, in packed-row order for the one-
# transfer statics scatter (client_key rides bitcast through the i32 pack)
_STATIC_COLS = (
    ("client_key", 0, "uint32"),
    ("origin_slot", NULL, "int32"),
    ("origin_clock", 0, "int32"),
    ("right_slot", NULL, "int32"),
    ("right_clock", 0, "int32"),
    ("origin_row", NULL, "int32"),
)

if HAS_JAX:
    import functools

    @profiled("scatter_statics")
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _scatter_statics(statics, packed):
        """All six resident-column updates in ONE device dispatch from ONE
        packed [8, K] i32 transfer (rows: doc idx, row idx, then the six
        value columns in _STATIC_COLS order)."""
        d, r = packed[0], packed[1]
        out = {}
        for j, (key, _fill, dtype) in enumerate(_STATIC_COLS):
            v = packed[2 + j]
            if dtype == "uint32":
                v = jax.lax.bitcast_convert_type(v, jnp.uint32)
            out[key] = statics[key].at[d, r].set(v)
        return out


def _phase(name: str):
    """jax.profiler annotation around one flush phase — visible in any
    active jax.profiler trace (the per-phase tracing SURVEY.md §5 calls
    for); free when no trace is being captured."""
    if not HAS_JAX:
        return contextlib.nullcontext()
    return jax.profiler.TraceAnnotation(f"ytpu.{name}")


class _PhasePair:
    """Two stacked phase contexts without ExitStack overhead — _phase_ctx
    sits on the per-flush hot path (7 entries per flush)."""

    __slots__ = ("_outer", "_inner")

    def __init__(self, outer, inner):
        self._outer = outer
        self._inner = inner

    def __enter__(self):
        self._outer.__enter__()
        self._inner.__enter__()
        return self

    def __exit__(self, *exc):
        try:
            return self._inner.__exit__(*exc)
        finally:
            self._outer.__exit__(*exc)


def _pipeline_on() -> bool:
    """YTPU_FLUSH_PIPELINE knob: pipelined flush is the default; ``0`` /
    ``false`` / ``off`` restores the fully synchronous dispatch (the A/B
    lane — byte-identical output is the pipeline's correctness bar)."""
    return os.environ.get("YTPU_FLUSH_PIPELINE", "1").lower() not in (
        "0", "false", "off",
    )


def _is_ready(arr) -> bool:
    """Non-blocking device-completion probe; a backend without is_ready
    reports ready (the blocking _wait below is still the safety fence)."""
    try:
        return bool(arr.is_ready())
    except Exception:
        return True


class _StageSlot:
    """One half of the double-buffered staging pair: a reusable host lanes
    buffer plus the device dispatch output that last consumed it (the
    reuse fence — jnp.asarray may alias host memory zero-copy, so the
    buffer must not be rewritten while that dispatch is in flight)."""

    __slots__ = ("buf", "marker")

    def __init__(self):
        self.buf = None
        self.marker = None


class _PackTimer:
    """Times one host pack and books it as overlapped when a device
    dispatch was still outstanding (dispatched this flush, not yet
    blocked on) at pack start — the numerator of the bench overlap
    fraction (t_pack_overlap_s / t_pack_s).  This is pack work the
    synchronous A/B lane would have serialized behind a blocking wait;
    it does not re-probe readiness, because an async backend that
    happens to finish early (CPU) still proves the host never waited —
    the honest wait time is t_device_wait_s."""

    __slots__ = ("_pl", "_t0", "_overlap")

    def __init__(self, pl):
        self._pl = pl
        self._t0 = 0.0
        self._overlap = False

    def __enter__(self):
        self._overlap = self._pl.outstanding > 0
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._overlap:
            self._pl.t_pack_overlap_s += time.perf_counter() - self._t0
        return False


class _FlushPipeline:
    """Pipelined flush state machine (ISSUE 12): stage N+1's host-side
    pack overlaps stage N's device execution.

    JAX dispatch is asynchronous — a jitted call returns as soon as the
    work is enqueued — so the pipeline needs no threads: it only has to
    (a) keep packing into a DIFFERENT staging buffer than the one the
    in-flight dispatch may still be reading (``acquire`` alternates the
    double-buffered pair and blocks — counted as t_device_wait_s — only
    when both halves are still feeding the device), and (b) account
    honestly for what overlapped (``_PackTimer``).  ``sync=True`` is the
    YTPU_FLUSH_PIPELINE=0 A/B lane: every dispatch blocks to completion
    before the host proceeds.

    One instance persists across flushes (the staging pair and in-flight
    markers carry over, so steady state reallocates nothing);
    ``begin_flush`` resets only the per-flush counters."""

    __slots__ = (
        "sync", "t_pack_overlap_s", "t_device_wait_s", "n_dispatches",
        "max_depth", "outstanding", "_slots", "_turn", "_inflight",
    )

    def __init__(self):
        self.sync = False
        self.t_pack_overlap_s = 0.0
        self.t_device_wait_s = 0.0
        self.n_dispatches = 0
        self.max_depth = 0
        # dispatches this flush the host has not blocked on (the
        # _PackTimer overlap predicate; reset per flush so read-backs
        # between flushes can't inflate it)
        self.outstanding = 0
        self._slots = (_StageSlot(), _StageSlot())
        self._turn = 0
        self._inflight: list = []

    def begin_flush(self, sync: bool) -> None:
        self.sync = sync
        self.t_pack_overlap_s = 0.0
        self.t_device_wait_s = 0.0
        self.n_dispatches = 0
        self.max_depth = 0
        self.outstanding = 0

    def in_flight(self) -> bool:
        """Prune completed dispatches; True while the device is busy."""
        self._inflight = [a for a in self._inflight if not _is_ready(a)]
        return bool(self._inflight)

    def _wait(self, arr) -> None:
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(arr)
        except Exception:
            pass
        self.t_device_wait_s += time.perf_counter() - t0
        self.outstanding = 0

    def acquire(self, shape, dtype) -> _StageSlot:
        """Next staging buffer of the pair, ready for host writes.  The
        slot's previous dispatch (two dispatches back in steady state)
        must have consumed the buffer before it is rewritten; any block
        here is real pipeline back-pressure, counted as device wait."""
        self._turn ^= 1
        slot = self._slots[self._turn]
        if slot.marker is not None:
            if not _is_ready(slot.marker):
                self._wait(slot.marker)
            slot.marker = None
        buf = slot.buf
        if buf is None or buf.shape != tuple(shape) or buf.dtype != dtype:
            slot.buf = buf = np.empty(shape, dtype)
        return slot

    def pack(self) -> _PackTimer:
        return _PackTimer(self)

    def dispatched(self, marker, slot: _StageSlot | None = None) -> None:
        """Book one device dispatch.  ``marker`` is a dispatch output
        array — output-ready implies every input (including ``slot``'s
        staging buffer) has been consumed."""
        self.n_dispatches += 1
        if slot is not None:
            slot.marker = marker
        if self.sync:
            self._wait(marker)
            self._inflight = []
            if slot is not None:
                slot.marker = None
            return
        self.outstanding += 1
        self._inflight = [a for a in self._inflight if not _is_ready(a)]
        self._inflight.append(marker)
        if len(self._inflight) > self.max_depth:
            self.max_depth = len(self._inflight)


class BatchEngine:
    """Applies binary Yjs updates to a batch of docs on device.

    Parameters
    ----------
    n_docs: batch size.
    root_name: the default root type for text()/rows_in_order() when no
        name is passed; any number of root text/list/map types per doc —
        and the shared types nested inside them — are integrated on
        device (subdocs fall back to the CPU core per doc).
    """

    def __init__(
        self,
        n_docs: int,
        root_name: str = "text",
        mesh=None,
        gc: bool = False,
        compact_min_rows: int = 512,
        policy: str = "auto",
    ):
        if policy not in ("auto", "cpu", "device"):
            raise ValueError(f"unknown policy {policy!r}")
        self.n_docs = n_docs
        self.root_name = root_name
        self.mesh = mesh
        self.gc = gc
        self.compact_min_rows = compact_min_rows
        # backend policy: "auto" demotes out-of-scope docs to the CPU core,
        # "cpu" serves every doc on the CPU core (lazily, no device work),
        # "device" records demotions as in auto (state stays consistent and
        # no data is lost) but the Provider raises while any exist
        self.policy = policy
        # export source: host list walk (default — zero device round trips)
        # or device rank kernel (the verification path; the test suite sets
        # YTPU_EXPORT_DEVICE=1 so every oracle comparison validates the
        # DEVICE state, and a dedicated test pins host==device)
        self.export_from_device = os.environ.get("YTPU_EXPORT_DEVICE") == "1"
        # per-doc row count at the last compaction (growth trigger)
        self._rows_at_compact = [0] * n_docs
        # per-doc stats of the most recent flush's compactions
        self.last_compaction: list[dict] | None = None
        # doc.on('update') seam: callbacks (doc_idx, update_bytes) invoked
        # after each flush with the flush's incremental update per doc
        self._update_listeners: list = []
        # typed-event seam: doc idx -> callbacks(doc, events) where events
        # are YEvent-shaped dicts computed from the step plan (reference
        # observe/observeDeep, AbstractType.js:360-389)
        self._event_listeners: dict[int, list] = {}
        self._metrics_dev: dict | None = None
        self._sharded_step = None
        # cached sharded state-vector callables keyed by n_slots (jit's
        # cache is per function identity — rebuilding retraces every call)
        self._sharded_sv: dict[int, object] = {}
        # cached sharded bulk-apply callables keyed by lane bucket shape
        self._sharded_apply: dict[tuple, object] = {}
        # explicit placement: a meshed engine pins EVERY host->device
        # transfer to the mesh's devices so it can never touch the default
        # backend (the mesh may be a virtual CPU mesh while the default
        # platform is a real accelerator — the multichip dry-run context)
        self._ns_batch = None  # [B, ...] arrays, doc axis sharded
        self._ns_repl = None  # small aux arrays, replicated over the mesh
        if mesh is not None:
            doc_axis = mesh.axis_names[0]
            axis_size = mesh.shape[doc_axis]
            if n_docs % axis_size != 0:
                raise ValueError(
                    f"n_docs={n_docs} must be a multiple of the {doc_axis!r} "
                    f"axis size {axis_size}"
                )
            from jax.sharding import NamedSharding, PartitionSpec

            self._ns_batch = NamedSharding(mesh, PartitionSpec(doc_axis))
            self._ns_repl = NamedSharding(mesh, PartitionSpec())
            from ..parallel.mesh import sharded_batch_step

            self._sharded_step = sharded_batch_step(mesh, doc_axis)
        self.mirrors: list = [make_mirror(root_name) for _ in range(n_docs)]
        # CPU fallback docs (Provider gating): doc idx -> Doc
        self.fallback: dict[int, Doc] = {}
        # every demotion ever, with its reason — scope gaps are measurable,
        # not silent (each entry: {"doc", "reason"})
        self.demotions: list[dict] = []
        # observability bundle: metrics registry + flush-history ring +
        # host span tracer (host-side per-phase timers + batch stats of
        # every flush live in obs.history; last_flush_metrics is the
        # compatibility view of the newest entry)
        self.obs = EngineObs()
        # resilience (ISSUE 2): per-doc failure isolation.  Strict mode
        # (YTPU_RESILIENCE_DISABLED=1) restores the pre-resilience
        # contract — integration failures raise out of flush()
        self._strict = os.environ.get("YTPU_RESILIENCE_DISABLED") == "1"
        self.health = HealthTracker(obs=self.obs)
        self.dead_letters = DeadLetterQueue()
        # every transactional per-doc rollback, with its reason (the
        # rollback subset of self.demotions)
        self.rollbacks: list[dict] = []
        self._update_log: list[list[tuple[bytes, bool]]] = [[] for _ in range(n_docs)]
        # warm-promotion column scatters deferred to the next flush /
        # device read-back: doc -> (right, deleted, seg_heads) rows
        self._pending_hydration: dict[int, tuple] = {}
        # persistent device state (no left-link array: order is ranked from
        # right links with a host-known membership mask)
        self._cap = 0  # row capacity N (arrays are [B, N+1] with scratch row)
        self._seg_cap = 0  # segment capacity S (starts is [B, S+1])
        self._right = None
        self._deleted = None
        self._starts = None
        # resident immutable columns, updated by per-flush row scatters —
        # steady-state flush transfer scales with the DELTA, not with B*cap
        self._statics: dict | None = None
        # rows per doc already uploaded and still valid on device
        self._uploaded_rows = [0] * n_docs
        # pipelined flush state (ISSUE 12): double-buffered staging pair +
        # in-flight dispatch markers persist ACROSS flushes so steady
        # state neither reallocates nor stalls; per-flush counters reset
        # in _flush.  Sync (A/B) mode is re-read from YTPU_FLUSH_PIPELINE
        # at every flush.
        self._pl = _FlushPipeline()
        # device-table bytes (re)allocated during the current flush — 0 in
        # steady state, where every dispatch donates in place
        self._flush_realloc_bytes = 0
        # slots that ever accepted traffic (cleared by reset_doc): feeds
        # the ytpu_prof_slot_occupancy gauge in O(1) per update
        self._active_docs: set[int] = set()

    # -- update ingestion ---------------------------------------------------

    def queue_update(self, doc: int, update: bytes, v2: bool = False) -> bool:
        """Queue one update for ``doc``; returns True when accepted.

        False means the bytes were diverted to :attr:`dead_letters`
        instead of entering the pipeline: the doc is quarantined, or (on
        the CPU-served path, where apply is immediate) the update failed
        to apply.  Callers that track dirtiness should only mark dirty
        on True."""
        if (
            not self._strict
            and self.health.tracked
            and not self.health.admissible(doc)
        ):
            self._dead_letter(doc, update, v2, "quarantined")
            return False
        fb = self.fallback.get(doc)
        if fb is None and self.policy == "cpu":
            fb = self._cpu_serve(doc)
        if fb is not None:
            # CPU-served docs apply directly; the log is dead weight for them
            try:
                (apply_update_v2 if v2 else apply_update)(fb, update)
            except Exception as e:
                if self._strict:
                    raise
                reason = f"cpu-apply: {type(e).__name__}: {e}"
                self._dead_letter(doc, update, v2, reason)
                self.health.record_failure(doc, reason)
                return False
            if self.health.tracked:
                self.health.record_success(doc)
        else:
            self._update_log[doc].append((update, v2))
            self.mirrors[doc].ingest(update, v2)
        self._active_docs.add(doc)
        return True

    def _dead_letter(self, doc: int, update: bytes, v2: bool, reason: str) -> None:
        self.dead_letters.append(doc, update, v2, reason)
        self.obs.dead_lettered(
            reason, len(self.dead_letters), self.dead_letters.dropped
        )

    def _cpu_serve(self, doc: int) -> Doc:
        """Route a doc to the CPU reference core by configuration (policy
        'cpu') — not a demotion, so it is not recorded as one."""
        fb = Doc(gc=False)
        self.fallback[doc] = fb
        self.mirrors[doc] = DocMirror(self.root_name)  # dead mirror
        fb.on("update", lambda u, origin, d, i=doc: self._emit(i, u))
        if doc in self._event_listeners:
            self._attach_cpu_events(doc, fb)
        return fb

    def on_update(self, callback) -> None:
        """Register ``callback(doc_idx, update_bytes)`` — called after each
        flush with that flush's incremental update per changed doc (the
        reference doc.on('update') broadcast contract,
        Transaction.js:339-352).  Demoted docs keep emitting via their CPU
        Doc's own update events."""
        self._update_listeners.append(callback)

    def off_update(self, callback) -> None:
        self._update_listeners.remove(callback)

    def observe(self, doc: int, callback) -> None:
        """Register ``callback(doc_idx, events)`` for one doc: after each
        flush that changes it, ``events`` is a list of YEvent-shaped dicts
        ``{"path", "delta", "keys"}`` — path[0] is the root type name,
        deeper elements are map keys / list indices (reference
        YEvent.path + YEvent.changes).  Demoted docs deliver the same
        shape from the CPU core's transactions.

        Numeric list positions in ``path`` match the reference getPathTo
        (YEvent.js:207-228) exactly: one per undeleted ITEM before the
        target, with mirror rows grouped into CPU-merged-item runs so the
        count equals what a CPU doc reports even though the mirror merges
        lazily (ops/events.py _path_of / _rows_one_cpu_item; parity
        pinned by test_engine_events.py::test_event_path_parity_*)."""
        self._event_listeners.setdefault(doc, []).append(callback)
        fb = self.fallback.get(doc)
        if fb is not None:
            self._attach_cpu_events(doc, fb)

    def unobserve(self, doc: int, callback) -> None:
        self._event_listeners[doc].remove(callback)
        if not self._event_listeners[doc]:
            del self._event_listeners[doc]

    def _attach_cpu_events(self, doc: int, fb: Doc) -> None:
        if getattr(fb, "_ytpu_events_attached", False):
            return
        fb._ytpu_events_attached = True
        from ..ids import find_root_type_key
        from ..types.events import YEvent, get_path_to

        def after_transaction(transaction, d, i=doc):
            cbs = self._event_listeners.get(i)
            if not cbs:
                return
            events = []
            for typ in transaction.changed:
                root = typ
                while root._item is not None:
                    root = root._item.parent
                ev = YEvent(typ, transaction)
                changes = ev.changes
                if not changes["delta"] and not changes["keys"]:
                    continue
                events.append({
                    "path": [find_root_type_key(root)]
                    + get_path_to(root, typ),
                    "delta": changes["delta"],
                    "keys": changes["keys"],
                })
            if events:
                for cb in cbs:
                    cb(i, events)

        fb.on("afterTransaction", after_transaction)

    def _emit(self, doc: int, update: bytes) -> None:
        self.obs.update_emitted(len(update))
        for cb in self._update_listeners:
            cb(doc, update)

    def _demote(
        self,
        doc: int,
        pre_sv: dict[int, int] | None = None,
        reason: str = "unspecified",
    ) -> Doc:
        """Move a doc to the CPU reference path by replaying its update log.

        When the doc is observed, the CPU event bridge attaches at the
        point of the replay where the pre-flush state vector is covered
        (the log prefix reproduces it exactly), so the demoting flush's
        own changes still deliver typed events — only historical replay
        stays silent."""
        self.demotions.append({"doc": doc, "reason": reason})
        self.obs.demoted(doc, reason)
        fb = Doc(gc=False)
        observed = doc in self._event_listeners
        attached = False
        if observed and not pre_sv:
            self._attach_cpu_events(doc, fb)
            attached = True
        for update, v2 in self._update_log[doc]:
            if observed and not attached:
                from ..core import get_state_vector

                sv = get_state_vector(fb.store)
                if all(sv.get(c, 0) >= v for c, v in pre_sv.items()):
                    self._attach_cpu_events(doc, fb)
                    attached = True
            try:
                (apply_update_v2 if v2 else apply_update)(fb, update)
            except Exception as e:
                # a log entry even the CPU reference core rejects cannot
                # be replayed anywhere: keep the bytes recoverable and
                # finish the demotion with the entries that do apply
                if self._strict:
                    raise
                self._dead_letter(
                    doc, update, v2, f"replay: {type(e).__name__}: {e}"
                )
        self.fallback[doc] = fb
        self.mirrors[doc] = DocMirror(self.root_name)  # dead mirror
        plan_cache.note_invalidation("demote")
        self._update_log[doc] = []
        self._uploaded_rows[doc] = 0
        if self._update_listeners:
            # emit the demoting flush's novelty, then live-forward the
            # fallback doc's own update events
            from ..updates import encode_state_as_update, encode_state_vector
            from ..coding import DSEncoderV1
            from ..updates import write_state_vector

            enc_sv = None
            if pre_sv:
                e = DSEncoderV1()
                write_state_vector(e, pre_sv)
                enc_sv = e.to_bytes()
            novelty = encode_state_as_update(fb, enc_sv)
            if novelty:
                self._emit(doc, novelty)
        fb.on("update", lambda u, origin, d, i=doc: self._emit(i, u))
        if doc in self._event_listeners:
            self._attach_cpu_events(doc, fb)
        return fb

    def _isolate_failure(self, doc: int, exc: Exception, pre_sv=None) -> None:
        """Transactional per-doc rollback: contain one doc's failed
        integration without touching the rest of the batch.

        The update log is the transaction journal — every entry is
        re-validated, malformed entries are stripped to the dead-letter
        queue (bytes + reason preserved), and :meth:`_demote` replays
        the surviving prefix into a fresh CPU doc.  That replay IS the
        rollback: it rebuilds the doc's last good state, and replacing
        the mirror discards whatever poison its ``_incoming`` held, so
        the failure cannot re-wedge later flushes."""
        reason = f"{type(exc).__name__}: {exc}"
        clean: list[tuple[bytes, bool]] = []
        for update, v2 in self._update_log[doc]:
            try:
                validate_update(update, v2)
            except InvalidUpdate as ve:
                self._dead_letter(doc, update, v2, f"invalid-update: {ve}")
            else:
                clean.append((update, v2))
        self._update_log[doc] = clean
        self.rollbacks.append({"doc": doc, "reason": reason})
        self.obs.rollback(doc, reason)
        self.health.record_failure(doc, reason)
        self._demote(doc, pre_sv, reason=f"rollback: {reason}")

    def replay_dead_letters(
        self, doc: int | None = None, seqs=None, repair=None,
        readmit: bool = False, max_letters: int | None = None,
    ) -> dict:
        """Re-inject dead letters through the normal ingestion path.

        ``repair`` is an optional ``callable(DeadLetter) -> bytes | None``
        applied first: return fixed bytes to replay, or None to leave
        the letter queued (counted as requeued).  ``readmit=True``
        clears the targeted docs' health records first (operator
        override of quarantine backoff).  Letters that still fail
        validation or admission are re-dead-lettered and counted as
        failed.  Work per invocation is bounded: at most ``max_letters``
        (``YTPU_DLQ_REPLAY_BATCH``, default 256; 0 = unbounded) letters
        are taken, the rest stay queued and are reported as
        ``truncated`` (metered by
        ``ytpu_resilience_dlq_replay_truncated_total``) so a deep DLQ
        cannot stall a flush tick or an admission drain.  Returns
        ``{"replayed", "requeued", "failed", "truncated"}``."""
        if readmit:
            self.health.reset(doc)
        if max_letters is None:
            try:
                max_letters = int(
                    os.environ.get("YTPU_DLQ_REPLAY_BATCH", "256")
                )
            except ValueError:
                max_letters = 256
        cap = max_letters if max_letters and max_letters > 0 else None
        replayed = requeued = failed = 0
        truncated = 0
        if cap is not None:
            matching = self.dead_letters.count_matching(doc=doc, seqs=seqs)
            truncated = max(0, matching - cap)
        for e in self.dead_letters.take(doc=doc, seqs=seqs, limit=cap):
            update = e.update
            if repair is not None:
                fixed = repair(e)
                if fixed is None:
                    self.dead_letters.append(e.doc, e.update, e.v2, e.reason)
                    requeued += 1
                    continue
                update = bytes(fixed)
            try:
                validate_update(update, e.v2)
            except InvalidUpdate as ve:
                self._dead_letter(e.doc, update, e.v2, f"replay-invalid: {ve}")
                failed += 1
                continue
            if self.queue_update(e.doc, update, e.v2):
                replayed += 1
            else:
                failed += 1  # inadmissible: re-dead-lettered by queue_update
        self.obs.replayed(replayed)
        if truncated:
            self.obs.replay_truncated(truncated)
        return {
            "replayed": replayed,
            "requeued": requeued,
            "failed": failed,
            "truncated": truncated,
        }

    def resilience_snapshot(self) -> dict:
        """JSON-able view of the failure-isolation state (bench/expo)."""
        return {
            "strict": self._strict,
            "health": self.health.summary(),
            "docs": self.health.records(),
            "dead_letters": self.dead_letters.snapshot(),
            "n_rollbacks": len(self.rollbacks),
            "n_demotions": len(self.demotions),
        }

    # -- device placement ---------------------------------------------------

    def _put_b(self, x):
        """Place a batch-leading [B, ...] array: doc-axis sharded over the
        mesh, or the default device when unmeshed."""
        if self._ns_batch is None:
            return jnp.asarray(x)
        return jax.device_put(np.asarray(x), self._ns_batch)

    def _put_r(self, x):
        """Place an auxiliary array replicated over the mesh (or default
        device when unmeshed)."""
        if self._ns_repl is None:
            return jnp.asarray(x)
        return jax.device_put(np.asarray(x), self._ns_repl)

    # -- device state management -------------------------------------------

    _STATIC_COLS = _STATIC_COLS

    def _ensure_capacity(self, n_rows: int, n_segs: int) -> None:
        cap = _bucket(n_rows)
        seg_cap = _bucket(n_segs, 8)
        if (
            cap <= self._cap
            and seg_cap <= self._seg_cap
            and self._right is not None
        ):
            return
        b = self.n_docs
        old_cap, old_seg = self._cap, self._seg_cap
        self._cap = max(cap, self._cap)
        self._seg_cap = max(seg_cap, self._seg_cap)

        # allocate/grow ON DEVICE: jnp.full / device pad compile to tiny
        # programs, where a host np.full + device_put ships B*(cap+1)
        # int32s over the link (~6MB per 1024-doc engine — seconds of a
        # tunneled backend's bandwidth, stealing the planner's host core)
        def fresh(shape, fill, dtype):
            arr = jnp.full(shape, fill, dtype)
            if self._ns_batch is not None:
                arr = jax.device_put(arr, self._ns_batch)
            return arr

        def grow(old, old_w, new_w, fill, dtype):
            out = fresh((b, new_w), fill, dtype)
            if old_w:
                out = jax.lax.dynamic_update_slice(
                    out, old[:, :old_w].astype(dtype), (0, 0)
                )
            if self._ns_batch is not None:
                out = jax.device_put(out, self._ns_batch)
            return out

        if self._right is None:
            self._right = fresh((b, self._cap + 1), NULL, jnp.int32)
            self._deleted = fresh((b, self._cap + 1), False, jnp.bool_)
            self._starts = fresh((b, self._seg_cap + 1), NULL, jnp.int32)
        else:
            # old scratch column (index old_cap) resets to padding
            self._right = grow(
                self._right, old_cap, self._cap + 1, NULL, jnp.int32
            )
            self._deleted = grow(
                self._deleted, old_cap, self._cap + 1, False, jnp.bool_
            )
            self._starts = grow(
                self._starts, old_seg, self._seg_cap + 1, NULL, jnp.int32
            )
        # donation bookkeeping: a grown table is a fresh allocation, so
        # this flush cannot have updated device state purely in place
        self._flush_realloc_bytes += int(
            self._right.nbytes + self._deleted.nbytes + self._starts.nbytes
        )
        # grow the resident statics device-side (pad, no host round trip).
        # Allocation is lazy: the bulk-apply path never reads them on
        # device, so an apply-only engine spends no HBM or transfer on
        # statics at all (_ensure_statics allocates on first levels/seq
        # dispatch).
        if self._statics is not None:
            old_statics = self._statics
            self._statics = {}
            for key, fill, dtype in self._STATIC_COLS:
                self._statics[key] = jnp.pad(
                    old_statics[key],
                    ((0, 0), (0, self._cap - old_cap)),
                    constant_values=fill,
                )
            self._flush_realloc_bytes += int(
                sum(v.nbytes for v in self._statics.values())
            )

    def _ensure_statics(self) -> None:
        if self._statics is not None:
            return
        b = self.n_docs
        self._statics = {
            key: self._put_b(np.full((b, self._cap + 1), fill, np.dtype(dtype)))
            for key, fill, dtype in self._STATIC_COLS
        }
        self._flush_realloc_bytes += int(
            sum(v.nbytes for v in self._statics.values())
        )
        # everything must (re-)upload into the fresh arrays
        self._uploaded_rows = [0] * b

    def _upload_statics(self, plans) -> None:
        """Scatter this flush's statics delta (its own dispatch — the
        levels/seq paths; the bulk path fuses the delta into
        kernels.apply_plan2 instead)."""
        self._ensure_statics()
        packed = self._statics_delta(plans)
        if packed is not None:
            self._dispatch("statics", self._put_r(packed))

    def _statics_delta(self, plans):
        """This flush's NEW/changed rows as one packed [8, K] i32 block
        (doc, row, six value columns; client_key bitcast).

        A doc's immutable columns only change by appending rows — except
        when a pre-split cuts an existing run (origin_row coverage moves to
        the new fragment) or compaction renumbered the table, which both
        force a full re-upload of that doc."""
        doc_idx: list[np.ndarray] = []
        row_idx: list[np.ndarray] = []
        vals: dict[str, list[np.ndarray]] = {k: [] for k, _f, _d in self._STATIC_COLS}
        for i, p in plans.items():
            m = self.mirrors[i]
            n = m.n_rows
            start = 0 if len(p.splits) else self._uploaded_rows[i]
            if n <= start:
                continue
            cols = m.static_columns(start)
            doc_idx.append(np.full(n - start, i, np.int32))
            row_idx.append(np.arange(start, n, dtype=np.int32))
            for k in vals:
                vals[k].append(cols[k])
            self._uploaded_rows[i] = n
        if not doc_idx:
            return None
        d = np.concatenate(doc_idx)
        r = np.concatenate(row_idx)
        # pad to a power-of-two bucket so the scatter compiles once per
        # bucket, not once per delta size; padding lanes write the scratch
        # row (index cap) of doc 0, whose contents are never read.  ONE
        # packed [8, K] transfer: per-array transfers each pay full link
        # latency on tunneled backends.
        total = len(d)
        padded = _bucket_lanes(total, 64)
        packed = np.empty((2 + len(self._STATIC_COLS), padded), np.int32)
        packed[0, :total] = d
        packed[0, total:] = 0
        packed[1, :total] = r
        packed[1, total:] = self._cap
        for j, (k, fill, dtype) in enumerate(self._STATIC_COLS):
            v = np.concatenate(vals[k])
            if dtype == "uint32":
                v = v.astype(np.uint32).view(np.int32)
            packed[2 + j, :total] = v
            packed[2 + j, total:] = fill
        return packed

    # -- compaction ---------------------------------------------------------

    def _maybe_compact(self) -> None:
        """Amortized run-merge + GC: when a doc's table doubles since its
        last compaction, read back its links/deleted bits and rebuild the
        mirror + device state with adjacent runs merged (the engine-side
        analogue of the reference's per-transaction merge/GC passes,
        Transaction.js:165-238,299-332).  Keeps row count bounded by the
        doc's true run structure instead of its edit history."""
        todo = [
            i
            for i, m in enumerate(self.mirrors)
            if i not in self.fallback
            and m.n_rows >= max(self.compact_min_rows, 2 * self._rows_at_compact[i])
        ]
        if not todo or self._right is None:
            return
        self.last_compaction = self._compact_rows(todo, self.gc)

    def _compact_rows(self, todo: list[int], gc: bool) -> list[dict]:
        """Rebuild ``todo``'s mirrors compacted and scatter the new rows
        into the device tables; returns per-doc row stats.

        The mirror's host list/deleted state equals the device arrays by
        flush invariant (YTPU_EXPORT_DEVICE pins it), so merges are
        decided WITHOUT any device read-back; the device gets the
        rebuilt rows in one write-only scatter — the r3 gather+readback
        cycle was the 100k-doc scaling liability (VERDICT r3 weak #3)."""
        idx = self._put_r(np.asarray(todo, np.int32))
        cap1 = self._cap + 1
        seg1 = self._seg_cap + 1
        new_right = np.full((len(todo), cap1), NULL, np.int32)
        new_deleted = np.zeros((len(todo), cap1), bool)
        new_starts = np.full((len(todo), seg1), NULL, np.int32)
        stats = []
        for j, i in enumerate(todo):
            # a fresh rebuild supersedes any still-pending hydration
            self._pending_hydration.pop(i, None)
            m = self.mirrors[i]
            old_n = m.n_rows
            r, d, h = m.rebuild_compacted_self(gc)
            n_new = len(r)
            new_right[j, :n_new] = r
            new_deleted[j, :n_new] = d
            new_starts[j, : len(h)] = h
            self._rows_at_compact[i] = n_new
            self._uploaded_rows[i] = 0  # renumbered: statics re-upload
            stats.append(
                {"doc": i, "rows_before": old_n, "rows_after": n_new}
            )
        self._dispatch(
            "rows", idx, self._put_r(new_right), self._put_r(new_deleted),
            self._put_r(new_starts),
        )
        return stats

    def compact_docs(self, docs, gc: bool = True) -> list[dict]:
        """Forced tombstone/GC compaction of specific docs (the tier GC
        pass, ISSUE 7): rebuild their packed columns with gc'able
        deleted runs dropped NOW, regardless of the table-doubling
        heuristic — long-lived hot docs accumulate tombstones that the
        amortized pass never reaches.  Docs on the CPU fallback, with
        queued (unflushed) updates, or with no rows are skipped.
        Returns the same per-doc row stats as ``last_compaction``."""
        todo = [
            i
            for i in docs
            if i not in self.fallback
            and not self.mirrors[i]._incoming
            and self.mirrors[i].n_rows > 0
        ]
        if not todo or self._right is None:
            return []
        stats = self._compact_rows(todo, gc)
        self.last_compaction = stats
        return stats

    # -- doc eviction / tiering ---------------------------------------------

    def export_doc_columns(self, doc: int):
        """Detach and return slot ``doc``'s host mirror for warm tiering
        (ISSUE 7).  The mirror is self-contained host state — packed
        struct-of-arrays columns plus interned payloads, no engine
        references — so the caller can park it off-slot and re-install
        it later with :meth:`hydrate_doc_columns`.  Pair with
        :meth:`reset_doc` to actually free the slot.  Flush first:
        queued updates would stay behind in the slot's log."""
        if doc in self.fallback:
            raise ValueError(
                f"doc {doc} is CPU-served; its columns live in the "
                "fallback doc, not the packed tables"
            )
        if self.mirrors[doc]._incoming:
            raise RuntimeError(
                f"doc {doc} has un-integrated updates; flush before "
                "exporting"
            )
        return self.mirrors[doc]

    def hydrate_doc_columns(self, doc: int, mirror) -> dict:
        """Re-install an exported mirror into the (reset) slot ``doc``
        with NO decode round-trip (warm promotion, ISSUE 7): the host
        columns are rebuilt compacted and the device scatter is
        DEFERRED — it batches into the next flush dispatch (or the next
        device read-back) alongside any other pending hydrations, so
        the promotion itself is host-only work.  Statics lazily
        re-upload from row 0 on the next flush that needs them."""
        if doc in self.fallback:
            raise ValueError(f"doc {doc} is CPU-served; reset it first")
        if self.mirrors[doc].n_rows or self._update_log[doc]:
            raise RuntimeError(f"slot {doc} is not empty; reset_doc first")
        self.mirrors[doc] = mirror
        self._update_log[doc] = []
        r, d, h = mirror.rebuild_compacted_self(self.gc)
        self._ensure_capacity(max(1, len(r)), max(1, len(h)))
        self._pending_hydration[doc] = (r, d, h)
        self._rows_at_compact[doc] = len(r)
        self._uploaded_rows[doc] = 0
        if len(r):
            self._active_docs.add(doc)
        return {"rows": len(r), "segs": len(h)}

    def _apply_pending_hydrations(self) -> None:
        """Scatter every deferred hydration into the device tables in
        ONE write-only pass (the ``_compact_rows`` idiom).  Called at
        the top of flush and before any device read-back; a no-op when
        nothing is pending."""
        if not self._pending_hydration:
            return
        pend = self._pending_hydration
        self._pending_hydration = {}
        todo = sorted(pend)
        if self._right is None:
            self._ensure_capacity(1, 1)
        cap1 = self._cap + 1
        seg1 = self._seg_cap + 1
        new_right = np.full((len(todo), cap1), NULL, np.int32)
        new_deleted = np.zeros((len(todo), cap1), bool)
        new_starts = np.full((len(todo), seg1), NULL, np.int32)
        for j, i in enumerate(todo):
            r, d, h = pend[i]
            new_right[j, : len(r)] = r
            new_deleted[j, : len(d)] = d
            new_starts[j, : len(h)] = h
        idx = self._put_r(np.asarray(todo, np.int32))
        # hydrations land as stage-0 dispatches of the flush pipeline (or
        # immediately before a device read-back): the donating row scatter
        # sequences ahead of this flush's integrate dispatches on the
        # device stream, so the integrate kernels always see hydrated rows
        self._dispatch(
            "rows", idx, self._put_r(new_right), self._put_r(new_deleted),
            self._put_r(new_starts),
        )

    def reset_doc(self, doc: int) -> None:
        """Return one slot to its just-constructed state (provider
        release_doc, ISSUE 3): fresh mirror, empty update log, cleared
        health record, and the device rows blanked to the same fills a
        new engine allocates — the slot's next tenant starts from
        nothing.  The dead-letter queue is NOT touched here (the caller
        decides whether the slot's letters travel with the evicted
        doc)."""
        self.mirrors[doc] = make_mirror(self.root_name)
        plan_cache.note_invalidation("reset")
        self.fallback.pop(doc, None)
        self._pending_hydration.pop(doc, None)
        self._update_log[doc] = []
        self._uploaded_rows[doc] = 0
        self._rows_at_compact[doc] = 0
        self._active_docs.discard(doc)
        self._event_listeners.pop(doc, None)
        self.health.reset(doc)
        if self._right is not None:
            # blank the slot's device rows in place (same fills as the
            # initial allocation); statics re-upload from row 0 is
            # already forced by _uploaded_rows above
            self._right = self._right.at[doc].set(NULL)
            self._deleted = self._deleted.at[doc].set(False)
            self._starts = self._starts.at[doc].set(NULL)

    # -- flush: run one device integration step ----------------------------

    def _phase_ctx(self, name: str, **args):
        """One flush phase: the jax.profiler annotation (visible inside an
        active device profiler trace) stacked with an obs host span (always
        recorded, exported via export_chrome_trace)."""
        return _PhasePair(_phase(name), self.obs.tracer.span(f"ytpu.{name}", **args))

    def _finish_flush(self, metrics: dict) -> None:
        """The single exit point of every flush path: append to the flush
        ring (which serves last_flush_metrics) + update the registry.
        Pipeline bookkeeping lands here so EVERY exit — bulk, levels/seq,
        replay, and the empty flush — emits the full shared schema."""
        pl = self._pl
        metrics["t_pack_overlap_s"] = pl.t_pack_overlap_s
        metrics["t_device_wait_s"] = pl.t_device_wait_s
        metrics["pipeline_depth"] = pl.max_depth
        # donated: every dispatch this flush updated resident tables in
        # place (no B*cap growth allocation anywhere in the flush)
        metrics["flush_donated"] = int(
            pl.n_dispatches > 0 and self._flush_realloc_bytes == 0
        )
        metrics["realloc_bytes"] = self._flush_realloc_bytes
        self.obs.record_flush(metrics, row_capacity=self._cap)
        if self.obs.enabled:
            self._record_device_memory()

    def _record_device_memory(self) -> None:
        """Refresh the ytpu_prof device-memory gauges from the persistent
        device buffers (ISSUE 4 cost attribution).  Reads array metadata
        only — no device sync; accounting must never break a flush."""
        right = self._right
        if right is None:
            return
        try:
            tables = {
                "right_link": int(right.nbytes),
                "deleted": int(self._deleted.nbytes),
                "starts": int(self._starts.nbytes),
            }
            if self._statics is not None:
                tables["statics"] = int(
                    sum(v.nbytes for v in self._statics.values())
                )
            try:
                backend = next(iter(right.devices())).platform
            except Exception:
                backend = "unknown"
            self.obs.device_memory(
                tables,
                backend,
                len(self._active_docs) / max(1, self.n_docs),
            )
        except Exception:
            pass

    def flush(self) -> None:
        with self.obs.tracer.span("ytpu.flush"):
            self._flush()
            # one flush = one health-clock tick (quarantine backoff is
            # counted in flushes, keeping re-admission deterministic)
            self.health.tick()

    def _flush(self) -> None:
        t_start = time.perf_counter()
        # per-flush pipeline counters reset; the staging pair + in-flight
        # markers persist across flushes.  Sync (A/B) mode is re-read per
        # flush so tests can flip YTPU_FLUSH_PIPELINE between flushes.
        self._pl.begin_flush(sync=not _pipeline_on())
        self._flush_realloc_bytes = 0
        # deferred warm-promotion scatters land before anything reads or
        # integrates on top of the device link tables (pipeline stage 0)
        self._apply_pending_hydrations()
        with self._phase_ctx("compact"):
            self._maybe_compact()
        t_compact = time.perf_counter()
        plans = {}
        pre_svs: dict[int, dict[int, int]] = {}
        demoted_now = 0
        rolled_back = 0
        cache_hits = cache_misses = 0
        t_plan_cached = t_plan_cold = 0.0
        plan_fanout = 1  # docs co-planned by one whole-chunk planner call
        emitting = bool(self._update_listeners)
        observing = self._event_listeners
        # kernel selection: "apply" (default, meshed or not) ships the
        # planner's final link values in one conflict-free scatter;
        # "levels"/"seq" run YATA on device (the sharded levels step
        # serves YTPU_KERNEL=levels on a mesh)
        mode = os.environ.get("YTPU_KERNEL")
        if not mode:
            mode = "apply"
        want_levels = mode != "apply"
        # bulk path + native planner: ONE ymx_prepare_many call plans every
        # staged doc (the per-doc ctypes loop was 72% of distinct-doc e2e,
        # BENCH_r03); levels/seq and the Python mirror keep the doc loop
        # gate on planner availability, not any particular doc's mirror: a
        # demoted doc 0 must not silently disable the fast path fleet-wide
        use_batch = (
            not want_levels
            and native_plan_available()
            and any(isinstance(m, NativeMirror) for m in self.mirrors)
        )
        work: list = []  # batched path: (doc, mirror)
        with self._phase_ctx("plan"):
            if use_batch:
                for i, m in enumerate(self.mirrors):
                    if i in self.fallback or not isinstance(m, NativeMirror):
                        continue
                    if not m._incoming and not m._had_pending:
                        continue  # idle doc: nothing to plan or emit
                    if emitting or i in observing:
                        pre_svs[i] = m.state_vector()
                    work.append((i, m))
                plans = dict(work)  # presence for the empty-flush check
            else:
                cache = plan_cache.get_cache()
                seg_mode = segment_planner.plan_segment_mode()
                # device mode co-plans every cold DocMirror's anchors in
                # ONE batched kernel call (ISSUE 15): phase A runs per
                # doc in the loop, the whole-chunk segment plan lands
                # between, phase B finishes per doc below
                chunk_cold: list = []  # (doc, mirror, cache key, phase-A token)
                chunk_keys: set = set()
                # intra-flush duplicates of a chunked doc's key wait for
                # the leader's cache insert and replay it (the per-doc
                # loop got this for free by inserting before the next
                # lookup)
                chunk_dup: list = []  # (doc, mirror, cache key)
                for i, m in enumerate(self.mirrors):
                    if i in self.fallback:
                        continue
                    if not m._incoming and not m.has_pending():
                        continue  # idle doc: nothing to plan, upload, or emit
                    if emitting or i in observing:
                        pre_svs[i] = m.state_vector()
                    key = ent = None
                    if cache is not None:
                        key = m.plan_key(want_levels)
                        ent = cache.lookup(key)
                    t_d0 = time.perf_counter()
                    if ent is not None:
                        # hit: replay the cached post-prepare snapshot
                        # onto this mirror instead of re-planning
                        if isinstance(m, NativeMirror):
                            plans[i] = m.make_plan(m.adopt_cached(ent))
                        else:
                            m2, plans[i] = ent.clone()
                            # keep the mirror's object identity (engine
                            # internals and tests may hold references)
                            m.__dict__.clear()
                            m.__dict__.update(m2.__dict__)
                        cache_hits += 1
                        t_plan_cached += time.perf_counter() - t_d0
                        continue
                    if seg_mode == "device" and type(m) is DocMirror:
                        if key is not None and key in chunk_keys:
                            chunk_dup.append((i, m, key))
                            continue
                        try:
                            token = m.prepare_step_begin()
                        except UnsupportedUpdate as e:
                            self._demote(i, pre_svs.get(i), reason=str(e))
                            demoted_now += 1
                        except Exception as e:
                            if self._strict:
                                raise
                            self._isolate_failure(i, e, pre_svs.get(i))
                            demoted_now += 1
                            rolled_back += 1
                        else:
                            chunk_cold.append((i, m, key, token))
                            if key is not None:
                                chunk_keys.add(key)
                        t_plan_cold += time.perf_counter() - t_d0
                        continue
                    try:
                        plans[i] = m.prepare_step(want_levels=want_levels)
                    except UnsupportedUpdate as e:
                        self._demote(i, pre_svs.get(i), reason=str(e))
                        demoted_now += 1
                    except Exception as e:
                        # malformed bytes (or any integration fault):
                        # roll back and contain THIS doc; the rest of
                        # the batch flushes normally
                        if self._strict:
                            raise
                        self._isolate_failure(i, e, pre_svs.get(i))
                        demoted_now += 1
                        rolled_back += 1
                    else:
                        if key is not None:
                            cache_misses += 1
                            if isinstance(m, NativeMirror):
                                cache.insert_native(key, m, plans[i].counts)
                            else:
                                cache.insert_py(key, m, plans[i])
                    t_plan_cold += time.perf_counter() - t_d0
                if chunk_cold:
                    t_d0 = time.perf_counter()
                    try:
                        seg_plans = segment_planner.plan_chunk(
                            [
                                (t.queries, m._segment_snapshot)
                                for (_i, m, _k, t) in chunk_cold
                            ],
                            mode=seg_mode,
                            mesh=self.mesh,
                        )
                    except Exception:
                        # planner fault: fall back to per-doc planning
                        # in finish (a doc-level fault there still
                        # poisons/demotes only its own doc)
                        seg_plans = ["auto"] * len(chunk_cold)
                    co_planned = sum(
                        1 for (_i, _m, _k, t) in chunk_cold
                        if t.queries is not None
                    )
                    plan_fanout = max(plan_fanout, co_planned)
                    for (i, m, key, token), sp in zip(chunk_cold, seg_plans):
                        try:
                            plans[i] = m.prepare_step_finish(
                                token, sp, want_levels
                            )
                        except UnsupportedUpdate as e:
                            self._demote(i, pre_svs.get(i), reason=str(e))
                            demoted_now += 1
                        except Exception as e:
                            if self._strict:
                                raise
                            self._isolate_failure(i, e, pre_svs.get(i))
                            demoted_now += 1
                            rolled_back += 1
                        else:
                            if key is not None:
                                cache_misses += 1
                                cache.insert_py(key, m, plans[i])
                    t_plan_cold += time.perf_counter() - t_d0
                for i, m, key in chunk_dup:
                    t_d0 = time.perf_counter()
                    ent = cache.lookup(key) if cache is not None else None
                    if ent is not None:
                        m2, plans[i] = ent.clone()
                        m.__dict__.clear()
                        m.__dict__.update(m2.__dict__)
                        cache_hits += 1
                        t_plan_cached += time.perf_counter() - t_d0
                        continue
                    # leader demoted/failed before inserting: plan solo
                    try:
                        plans[i] = m.prepare_step(want_levels=want_levels)
                    except UnsupportedUpdate as e:
                        self._demote(i, pre_svs.get(i), reason=str(e))
                        demoted_now += 1
                    except Exception as e:
                        if self._strict:
                            raise
                        self._isolate_failure(i, e, pre_svs.get(i))
                        demoted_now += 1
                        rolled_back += 1
                    else:
                        cache_misses += 1
                        cache.insert_py(key, m, plans[i])
                    t_plan_cold += time.perf_counter() - t_d0
        t_plan = time.perf_counter()
        # ONE schema (obs.FLUSH_METRICS_SCHEMA) for every exit: each path
        # overwrites only the fields it measures, so the key set cannot
        # drift between the apply/levels/seq/batched/empty-flush paths
        metrics = new_flush_metrics(
            n_demoted=demoted_now,
            n_rolled_back=rolled_back,
            n_fallback_docs=len(self.fallback),
            t_compact_s=t_compact - t_start,
            t_plan_s=t_plan - t_compact,
            t_plan_cached_s=t_plan_cached,
            t_plan_cold_s=t_plan_cold,
            plan_cache_hits=cache_hits,
            plan_cache_misses=cache_misses,
            plan_threads=plan_fanout,
            plan_fastpath_structs=sum(
                getattr(p, "fastpath_structs", 0) or 0
                for p in plans.values()
                if p is not None and not isinstance(p, NativeMirror)
            ),
            plan_segment_fast=sum(
                getattr(p, "segment_fast", 0) or 0
                for p in plans.values()
                if p is not None and not isinstance(p, NativeMirror)
            ),
            plan_segment_residue=sum(
                getattr(p, "segment_residue", 0) or 0
                for p in plans.values()
                if p is not None and not isinstance(p, NativeMirror)
            ),
        )
        if not plans:
            metrics["t_total_s"] = time.perf_counter() - t_start
            self._finish_flush(metrics)
            return
        if use_batch:
            self._flush_bulk(
                work, pre_svs, emitting, metrics, t_start,
                observed=set(observing), native=True,
            )
            return
        if mode == "apply":
            self._flush_bulk(
                sorted(plans.items()), pre_svs, emitting, metrics, t_start,
                native=False,
            )
            return
        with self._phase_ctx("pack"), self._pl.pack():
            n_splits = _bucket(
                max((len(p.splits) for p in plans.values()), default=0), 1
            )
            n_sched = _bucket(
                max((len(p.sched) for p in plans.values()), default=0), 1
            )
            n_del = _bucket(
                max((len(p.delete_rows) for p in plans.values()), default=0), 1
            )
            n_lv = _bucket(
                max((p.n_levels for p in plans.values()), default=0), 1
            )
            w_lv = _bucket(
                max((p.max_width for p in plans.values()), default=0), 1
            )
            max_rows = max((p.n_rows for p in plans.values()), default=0)
            max_segs = max(
                (self.mirrors[i].n_segs for i in plans), default=0
            )
            # reserve >= 2*w_lv spare row slots per doc: the level kernel's
            # merged scatter uses two unique scratch lanes per schedule slot
            self._ensure_capacity(max_rows + 2 * w_lv, max_segs)
            b, cap = self.n_docs, self._cap

            splits = np.full((b, n_splits, 2), NULL, np.int32)
            sched = np.full((b, n_sched, 4), NULL, np.int32)
            lv_sched = np.full((b, n_lv, w_lv, 8), NULL, np.int32)
            dels = np.full((b, n_del), NULL, np.int32)
            for i, p in plans.items():
                if len(p.splits):
                    splits[i, : len(p.splits)] = p.splits
                if len(p.sched):
                    sched[i, : len(p.sched)] = p.sched
                if hasattr(p, "pack_into"):
                    p.pack_into(lv_sched[i])
                else:
                    for lv, entries in enumerate(p.packed_levels()):
                        if entries:
                            lv_sched[i, lv, : len(entries)] = entries
                if len(p.delete_rows):
                    dels[i, : len(p.delete_rows)] = p.delete_rows

            # EVERY doc needs its true row count here — masked scatter lanes
            # land at scratch_base+lane even for docs with no work this
            # flush, and must hit the padding region, not live rows
            scratch_base = np.asarray(
                [m.n_rows for m in self.mirrors], np.int32
            )

            self._upload_statics(plans)
            statics = self._statics
        t_pack = time.perf_counter()
        with self._phase_ctx("dispatch"):
            if mode == "seq":
                self._metrics_dev = None  # no sharded counters this flush
                self._dispatch(
                    "seq", statics, self._put_b(splits),
                    self._put_b(sched), self._put_b(dels),
                )
            else:
                # blockwise over the level axis (the long-context analogue,
                # SURVEY.md §5: long update logs are processed as fixed-size
                # schedule tiles).  Levels are causally ordered and the
                # device state persists between dispatches, so slicing by
                # level prefix is exact: splits run only in the first block,
                # deletes only in the last.  Bounds the padded [B, L, W, 8]
                # transfer and device buffer no matter how long the log is —
                # on the single-chip and the sharded (mesh) path alike.
                block = max(
                    1,
                    int(os.environ.get("YTPU_BLOCK_LEVELS", "0"))
                    or _block_levels(b, w_lv),
                )
                empty_splits = empty_dels = None
                if n_lv > block:  # multi-block: cache the no-op inputs
                    empty_splits = self._put_b(np.full((b, 1, 2), NULL, np.int32))
                    empty_dels = self._put_b(np.full((b, 1), NULL, np.int32))
                scratch_d = self._put_b(scratch_base)
                self._metrics_dev = None
                for c0 in range(0, n_lv, block):
                    c1 = min(n_lv, c0 + block)
                    self._dispatch(
                        "levels",
                        statics,
                        self._put_b(splits) if c0 == 0 else empty_splits,
                        self._put_b(lv_sched[:, c0:c1]),
                        self._put_b(dels) if c1 == n_lv else empty_dels,
                        scratch_d,
                    )
        t_dispatch = time.perf_counter()

        with self._phase_ctx("emit"):
            self._emit_phase(plans, pre_svs, emitting)
        t_emit = time.perf_counter()

        n_sched_entries = sum(len(p.sched8) for p in plans.values())
        lv_slots = b * n_lv * w_lv
        pending_docs = [i for i in plans if self.mirrors[i].has_pending()]
        metrics.update({
            "n_docs_flushed": sum(
                1
                for p in plans.values()
                if len(p.sched8) or len(p.splits) or len(p.delete_rows)
            ),
            "n_rows_max": max_rows,
            "n_sched_entries": n_sched_entries,
            "n_levels": n_lv,
            "level_width": w_lv,
            # fraction of the padded [B, L, W] schedule that is real work
            "schedule_occupancy": n_sched_entries / lv_slots if lv_slots else 0.0,
            "n_pending_docs": len(pending_docs),
            "pending_depth": sum(
                self.mirrors[i].pending_depth() for i in pending_docs
            ),
            "t_pack_s": t_pack - t_plan,
            "t_dispatch_s": t_dispatch - t_pack,
            "t_emit_s": t_emit - t_dispatch,
            "t_total_s": t_emit - t_start,
        })
        self._finish_flush(metrics)

    def _emit_phase(self, plans, pre_svs, emitting, observed=None) -> None:
        """Post-dispatch host work shared by both dispatch paths: update-log
        compaction + doc.on('update') novelty emission (overlaps the async
        device execution).  ``observed`` restricts event computation to a
        prepare-time listener snapshot (the batched path may not have
        built plan.sched for docs unobserved at prepare)."""
        if self.health.tracked:
            # every doc that reached emit integrated cleanly this flush
            for i in plans:
                self.health.record_success(i)
        for i in plans:
            m = self.mirrors[i]
            if len(self._update_log[i]) > 64 and not m.has_pending():
                self._update_log[i] = [(m.encode_state_as_update(), False)]
        if emitting:
            for i, p in plans.items():
                u = self.mirrors[i].encode_step_update(pre_svs[i], p)
                if u is not None:
                    self._emit(i, u)
        if self._event_listeners:
            from .events import compute_flush_events

            for i, p in plans.items():
                if observed is not None and i not in observed:
                    continue
                cbs = self._event_listeners.get(i)
                if not cbs:
                    continue
                events = compute_flush_events(
                    self.mirrors[i], p, pre_svs[i]
                )
                if events:
                    for cb in cbs:
                        cb(i, events)

    def _dispatch(self, kind, *args, slot=None):
        """THE one flush dispatch path (ISSUE 12): every device mutation of
        the resident tables — bulk lanes (per-doc python plans, native
        batched plans, and cached-plan replay alike), the levels/seq YATA
        step, the statics delta scatter, and whole-row rebuild scatters
        (compaction, deferred hydration) — funnels through here, so the
        pipeline bookkeeping (in-flight markers, staging-buffer fences,
        sync A/B mode) and any future kernel change land exactly once.

        kinds:
          "lanes"   (lanes, key)                    bulk-apply scatter
          "seq"     (statics, splits, sched, dels)  sequential YATA step
          "levels"  (statics, splits, lv_block, dels, scratch)  one
                    level-axis block (sharded or not; device metrics
                    accumulate across blocks)
          "statics" (packed,)                       resident-column delta
          "rows"    (idx, right, deleted, starts)   whole-row rebuild

        ``slot`` ties the dispatch to the staging buffer it consumes (the
        double-buffered pair's reuse fence).  All array args are already
        device-placed by the caller (_put_b/_put_r)."""
        dyn = (self._right, self._deleted, self._starts)
        if kind == "lanes":
            lanes, key = args
            k_dn, k_sp, k_h, k_d = key
            self._metrics_dev = None
            if self.mesh is not None:
                fn = self._sharded_apply.get(key)
                if fn is None:
                    from ..parallel.mesh import sharded_apply_plan

                    fn = sharded_apply_plan(
                        self.mesh, self.mesh.axis_names[0], *key
                    )
                    self._sharded_apply[key] = fn
                dyn, self._metrics_dev = fn(dyn, self._put_b(lanes))
            else:
                dyn = kernels.apply_plan2(
                    dyn, self._put_r(lanes[0]), k_dn, k_sp, k_h, k_d
                )
        elif kind == "seq":
            statics, splits, sched, dels = args
            dyn = kernels.batch_step(statics, dyn, splits, sched, dels)
        elif kind == "levels":
            statics, splits, lv_block, dels, scratch = args
            largs = (statics, dyn, splits, lv_block, dels, scratch)
            if self._sharded_step is not None:
                # metrics stay device scalars (converting would block the
                # async dispatch); accumulate across blocks
                dyn, m = self._sharded_step(*largs)
                self._metrics_dev = (
                    m
                    if self._metrics_dev is None
                    else {k: self._metrics_dev[k] + m[k] for k in m}
                )
            else:
                dyn = kernels.batch_step_levels(*largs)
        elif kind == "statics":
            (packed,) = args
            self._statics = _scatter_statics(self._statics, packed)
            self._pl.dispatched(next(iter(self._statics.values())), slot)
            return
        elif kind == "rows":
            idx, new_right, new_deleted, new_starts = args
            dyn = kernels.scatter_rows(
                *dyn, idx, new_right, new_deleted, new_starts
            )
        else:  # pragma: no cover - programming error
            raise ValueError(f"unknown dispatch kind {kind!r}")
        self._right, self._deleted, self._starts = dyn
        self._pl.dispatched(self._right, slot)

    def _flush_bulk(
        self, items, pre_svs, emitting, metrics, t_start,
        observed=frozenset(), native=True,
    ):
        """ONE bulk flush driver (tentpole, ISSUE 12): native batched
        plans (ymx_prepare_many / ymx_pack_apply), per-doc python plans,
        and cached-plan replay all stream through the same chunked
        pack -> dispatch pipeline.  Chunk k+1's host-side work (plan +
        pack into the double-buffered staging pair) overlaps chunk k's
        asynchronous device execution, and the donating apply kernels
        update the resident tables in place — steady-state flushes
        neither reallocate B*cap buffers nor block the host on the
        device.  YTPU_FLUSH_PIPELINE=0 restores the synchronous A/B
        lane (every dispatch blocks); output is byte-identical either
        way.

        ``items``: ``(doc, NativeMirror)`` pairs when ``native`` (planned
        here, chunk by chunk), ``(doc, plan)`` pairs otherwise (planned
        by _flush's plan phase, already doc-ordered)."""
        pl = self._pl
        chunk_sz = int(os.environ.get("YTPU_FLUSH_CHUNK", "256"))
        b = self.n_docs
        n_shards = 1 if self.mesh is None else self.mesh.shape[
            self.mesh.axis_names[0]
        ]
        b_loc = b // n_shards
        t_plan_acc = t_pack_acc = t_disp_acc = 0.0
        seg_base = plan_segment_stats() if native else (0, 0)
        stats_tot = np.zeros(4, np.int64)
        lanes_padded_tot = 0
        work_ok: list = []  # native: (doc, mirror, counts); py: (doc, plan)
        max_rows_all = 0
        acc = SimpleNamespace(
            cache=plan_cache.get_cache() if native else None,
            # events read plan.sched; skip building it otherwise
            want_sched=bool(self._event_listeners),
            cfg_threads=_native_plan_threads() if native else 1,
            plan_threads=1,
            cache_hits=0,
            cache_misses=0,
            t_cached=0.0,
            t_cold=0.0,
            demoted=metrics["n_demoted"],
            rolled_back=metrics["n_rolled_back"],
        )
        for c0 in range(0, len(items), chunk_sz):
            chunk = items[c0 : c0 + chunk_sz]
            t0 = time.perf_counter()
            if native:
                with self._phase_ctx("plan", chunk=c0 // chunk_sz,
                                     docs=len(chunk)):
                    chunk_ok = self._plan_chunk_native(chunk, pre_svs, acc)
            else:
                chunk_ok = chunk
            t1 = time.perf_counter()
            t_plan_acc += t1 - t0
            if not chunk_ok:
                continue
            with self._phase_ctx("pack", chunk=c0 // chunk_sz), pl.pack():
                if native:
                    slot, key, stats, max_rows = self._pack_chunk_native(
                        chunk_ok, b_loc, n_shards
                    )
                else:
                    slot, key, stats, max_rows = self._pack_chunk_py(
                        chunk_ok, b_loc, n_shards
                    )
                stats_tot += stats
                max_rows_all = max(max_rows_all, max_rows)
                # capacity is per shard; real lane counts (stats) sum across
                # shards, so the denominator must too or meshed runs report
                # occupancy inflated by n_shards (ADVICE r4)
                lanes_padded_tot += n_shards * sum(key)
                # the apply path never reads the device statics; mark touched
                # docs for full (re-)upload if a levels/seq flush ever runs
                for t in chunk_ok:
                    self._uploaded_rows[t[0]] = 0
                work_ok.extend(chunk_ok)
            t2 = time.perf_counter()
            t_pack_acc += t2 - t1
            # async dispatch: the device consumes this chunk's staged lanes
            # while the next loop iteration plans and packs on the host
            # (the staging slot fences its buffer against premature reuse)
            with self._phase_ctx("dispatch", chunk=c0 // chunk_sz):
                self._dispatch("lanes", slot.buf, key, slot=slot)
            t_disp_acc += time.perf_counter() - t2
        metrics["n_demoted"] = acc.demoted
        metrics["n_rolled_back"] = acc.rolled_back
        t_dispatch = time.perf_counter()
        with self._phase_ctx("emit"):
            if native:
                # real plan objects only where the emit phase will read
                # them: every doc when update listeners exist, observed
                # docs for events; the log-compaction walk touches keys
                # only.  The observed set is the PREPARE-TIME snapshot: a
                # listener registered mid-flush (e.g. from an update
                # callback) sees events from the next flush — plan.sched
                # for this one may not have been built (want_sched gate)
                plans = {
                    i: (m.make_plan(c) if emitting or i in observed else None)
                    for i, m, c in work_ok
                }
                self._emit_phase(plans, pre_svs, emitting, observed=observed)
            else:
                self._emit_phase(dict(work_ok), pre_svs, emitting)
        t_emit = time.perf_counter()

        if native:
            counts = (
                np.stack([c for _, _, c in work_ok])
                if work_ok
                else np.zeros((0, 16), np.int64)
            )
            n_flushed = int(
                ((counts[:, 12] > 0) | (counts[:, 13] > 0)
                 | (counts[:, 6] > 0)).sum()
            )
            pending_mask = counts[:, 8] == 1
            n_pending = int(pending_mask.sum())
            pending_depth = int(counts[pending_mask, 9].sum())
        else:
            n_flushed = sum(
                1
                for _, p in work_ok
                if len(p.link_rows) or len(p.head_segs) or len(p.delete_rows)
            )
            pending = [
                i for i, _ in work_ok if self.mirrors[i].has_pending()
            ]
            n_pending = len(pending)
            pending_depth = sum(
                self.mirrors[i].pending_depth() for i in pending
            )
        n_dense, n_sparse, n_heads, n_dels = (int(x) for x in stats_tot)
        lanes_real = n_dense + n_sparse + n_heads + n_dels
        metrics.update({
            "n_docs_flushed": n_flushed,
            "n_rows_max": max_rows_all,
            "n_sched_entries": n_dense + n_sparse,
            "n_levels": 1,
            "level_width": n_dense + n_sparse,
            # bulk path: fraction of dispatched scatter lanes that are real
            "schedule_occupancy": (
                lanes_real / lanes_padded_tot if lanes_padded_tot else 0.0
            ),
            "n_pending_docs": n_pending,
            "pending_depth": pending_depth,
            "t_pack_s": t_pack_acc,
            "t_dispatch_s": t_disp_acc,
            "t_emit_s": t_emit - t_dispatch,
            "t_total_s": t_emit - t_start,
        })
        if native:
            metrics.update({
                "t_plan_s": t_plan_acc,
                "t_plan_cached_s": acc.t_cached,
                "t_plan_cold_s": acc.t_cold,
                "plan_cache_hits": acc.cache_hits,
                "plan_cache_misses": acc.cache_misses,
                # widest worker pool any prepare batch in this flush
                # actually used — min(configured width, docs in the
                # batch); 1 when every doc was served from the plan cache
                "plan_threads": acc.plan_threads,
            })
            seg_now = plan_segment_stats()
            metrics["plan_segment_fast"] = max(0, seg_now[0] - seg_base[0])
            metrics["plan_segment_residue"] = max(
                0, seg_now[1] - seg_base[1]
            )
        self._finish_flush(metrics)

    def _plan_chunk_native(self, chunk, pre_svs, acc):
        """Plan one chunk of ``(doc, NativeMirror)`` work: cache hits
        adopt the cached post-prepare snapshot, cold group leaders plan
        via ONE ymx_prepare_many call, trailing same-key members clone
        their leader.  Per-doc error policy (demote / rollback) matches
        the python plan loop exactly; ``acc`` accumulates plan-phase
        bookkeeping across chunks.  Returns the surviving
        ``(doc, mirror, counts)`` triples in ascending doc order."""
        cache = acc.cache
        want_sched = acc.want_sched
        chunk_ok: list = []
        hits: list = []    # (doc, mirror, entry)
        cold: list = []    # (doc, mirror, key) — group leaders
        groups: dict = {}  # key -> trailing same-key members
        if cache is not None:
            for i, m in chunk:
                key = m.plan_key(False, want_sched)
                g = groups.get(key)
                if g is not None:
                    # intra-chunk duplicate (broadcast fan-out):
                    # cloned from the leader after it plans
                    g.append((i, m))
                    continue
                ent = cache.lookup(key)
                if ent is not None:
                    hits.append((i, m, ent))
                else:
                    groups[key] = []
                    cold.append((i, m, key))
        else:
            cold = [(i, m, None) for i, m in chunk]
        th0 = time.perf_counter()
        for i, m, ent in hits:
            chunk_ok.append((i, m, m.adopt_cached(ent)))
        acc.cache_hits += len(hits)
        acc.t_cached += time.perf_counter() - th0
        retry: list = []  # members whose leader failed
        if cold:
            tc0 = time.perf_counter()
            acc.cache_misses += len(cold)
            acc.plan_threads = max(
                acc.plan_threads, min(acc.cfg_threads, len(cold))
            )
            counts_all, rcs, staged_info = prepare_many(
                [(i, m) for i, m, _k in cold],
                want_levels=False,
                want_sched=want_sched,
                obs=self.obs,
            )
            for k, (i, m, key) in enumerate(cold):
                try:
                    m._finish_prepare(
                        int(rcs[k]), staged_info[k][0],
                        staged_info[k][1], counts_all[k],
                    )
                except UnsupportedUpdate as e:
                    self._demote(i, pre_svs.get(i), reason=str(e))
                    acc.demoted += 1
                    retry.extend(groups.get(key, ()))
                except Exception as e:
                    if self._strict:
                        raise
                    self._isolate_failure(i, e, pre_svs.get(i))
                    acc.demoted += 1
                    acc.rolled_back += 1
                    retry.extend(groups.get(key, ()))
                else:
                    chunk_ok.append((i, m, counts_all[k]))
                    members = groups.get(key)
                    if members:
                        # identical frontier + staged bytes plan
                        # identically: clone the leader's live
                        # post-prepare state instead of
                        # re-walking each member
                        th1 = time.perf_counter()
                        src = SimpleNamespace(
                            h=m._h,
                            counts=counts_all[k],
                            pins=m._py_bufs,
                            frontier_after=m.plan_frontier,
                        )
                        for j, mj in members:
                            chunk_ok.append(
                                (j, mj, mj.adopt_cached(src))
                            )
                        acc.cache_hits += len(members)
                        plan_cache.note_hits(len(members))
                        acc.t_cached += time.perf_counter() - th1
                    if key is not None:
                        # post-prepare, pre-pack: the snapshot a
                        # future hit adopts before running the
                        # pack/dispatch phases itself
                        cache.insert_native(key, m, counts_all[k])
            acc.t_cold += time.perf_counter() - tc0
        if retry:
            # a leader's demote/isolate says nothing about its
            # members under the per-doc error policy — plan each
            # individually, exactly as a cache-off flush would
            tc0 = time.perf_counter()
            acc.cache_misses += len(retry)
            plan_cache.note_misses(len(retry))
            acc.plan_threads = max(
                acc.plan_threads, min(acc.cfg_threads, len(retry))
            )
            counts2, rcs2, staged2 = prepare_many(
                retry, want_levels=False, want_sched=want_sched,
                obs=self.obs,
            )
            for k, (i, m) in enumerate(retry):
                try:
                    m._finish_prepare(
                        int(rcs2[k]), staged2[k][0], staged2[k][1],
                        counts2[k],
                    )
                except UnsupportedUpdate as e:
                    self._demote(i, pre_svs.get(i), reason=str(e))
                    acc.demoted += 1
                except Exception as e:
                    if self._strict:
                        raise
                    self._isolate_failure(i, e, pre_svs.get(i))
                    acc.demoted += 1
                    acc.rolled_back += 1
                else:
                    chunk_ok.append((i, m, counts2[k]))
            acc.t_cold += time.perf_counter() - tc0
        # hit/leader/member completion order is cache-dependent;
        # pack and emit must see the same doc order either way
        chunk_ok.sort(key=lambda t: t[0])
        return chunk_ok

    def _pack_chunk_native(self, chunk_ok, b_loc, n_shards):
        """Stage one planned native chunk: grow capacity, size the
        per-shard lane widths, pick the int16 downshift, and run the
        native pack (ymx_pack_apply) writing straight into the acquired
        staging buffer.  Returns ``(slot, key, stats, max_rows)``."""
        counts = np.stack([c for _, _, c in chunk_ok])
        doc_idx = np.asarray([i for i, _, _ in chunk_ok], np.int64)
        max_rows = int(counts[:, 0].max(initial=0))
        self._ensure_capacity(
            max_rows, int(counts[:, 11].max(initial=0))
        )
        oob_r = int(self._cap + 1)
        oob_s = int(self._seg_cap + 1)
        shard = doc_idx // b_loc
        link = counts[:, 12]
        dense = counts[:, 14].astype(bool)

        def shard_max(values, mask, minimum, shard=shard):
            sums = np.bincount(
                shard[mask], weights=values[mask].astype(np.float64),
                minlength=n_shards,
            )
            return _bucket_lanes(int(sums.max(initial=0)), minimum)

        all_mask = np.ones(len(chunk_ok), bool)
        k_dn = shard_max(link, dense, 64)
        k_sp = shard_max(link, ~dense, 64)
        k_h = shard_max(counts[:, 13], all_mask, 8)
        k_d = shard_max(counts[:, 6], all_mask, 64)
        # int16 lanes when every index/count fits: half the flush
        # bytes over the host->device link (the distinct-path
        # bottleneck on tunneled backends)
        lane_dtype = (
            np.int16
            if max(oob_r, oob_s, int(link.max(initial=0))) <= 32767
            else np.int32
        )
        key = (k_dn, k_sp, k_h, k_d)
        lane_w = 4 * b_loc + k_dn + 2 * k_sp + 2 * k_h + k_d
        slot = self._pl.acquire((n_shards, lane_w), lane_dtype)
        lanes, stats = pack_apply_lanes(
            chunk_ok, doc_idx, b_loc, n_shards, key,
            oob_r, oob_s, int(NULL), lane_dtype, out=slot.buf,
        )
        slot.buf = lanes
        return slot, key, stats, max_rows

    def _pack_chunk_py(self, chunk_ok, b_loc, n_shards):
        """Python-mirror twin of :meth:`_pack_chunk_native`: bin one
        chunk of ``(doc, plan)`` pairs into the same counts-header +
        lanes layout (host-resolved YATA; see DocMirror._list_insert /
        plancore.cpp list_insert), packing into the acquired staging
        buffer.  Returns ``(slot, key, stats, max_rows)``.

        Per-doc counts ride in the lanes header; doc ids and dense row
        indices are derived ON DEVICE (kernels.apply_plan2), so the
        transfer carries the minimum: full-table ("dense") link loads
        ship values only.  One binning "shard" on a single device; the
        mesh path bins per device shard so each scatters its own lanes
        locally."""
        max_rows = max((p.n_rows for _, p in chunk_ok), default=0)
        max_segs = max(
            (self.mirrors[i].n_segs for i, _ in chunk_ok), default=0
        )
        self._ensure_capacity(max_rows, max_segs)
        oob_r = np.int32(self._cap + 1)
        counts = np.zeros((n_shards, 4, b_loc), np.int32)
        dense = [[] for _ in range(n_shards)]
        sp_r = [[] for _ in range(n_shards)]
        sp_v = [[] for _ in range(n_shards)]
        hd_s = [[] for _ in range(n_shards)]
        hd_v = [[] for _ in range(n_shards)]
        dl_r = [[] for _ in range(n_shards)]
        for i, p in chunk_ok:
            s, li = divmod(i, b_loc)
            k = len(p.link_rows)
            rows = np.asarray(p.link_rows, np.int32)
            vals = np.asarray(p.link_vals, np.int32)
            if k and k == p.n_rows and rows[-1] == k - 1:
                counts[s, 0, li] = k
                dense[s].append(vals)
            elif k:
                counts[s, 1, li] = k
                sp_r[s].append(rows)
                sp_v[s].append(vals)
            hn = len(p.head_segs)
            if hn:
                counts[s, 2, li] = hn
                hd_s[s].append(np.asarray(p.head_segs, np.int32))
                hd_v[s].append(np.asarray(p.head_vals, np.int32))
            dn = len(p.delete_rows)
            if dn:
                counts[s, 3, li] = dn
                dl_r[s].append(np.asarray(p.delete_rows, np.int32))

        def widths(parts_by_shard, minimum):
            return _bucket_lanes(
                max(
                    (sum(len(a) for a in parts) for parts in parts_by_shard),
                    default=0,
                ),
                minimum,
            )

        k_dn = widths(dense, 64)
        k_sp = widths(sp_r, 64)
        k_h = widths(hd_s, 8)
        k_d = widths(dl_r, 64)
        oob_s = np.int32(self._seg_cap + 1)

        def fill(out, parts, pad_val):
            flat = (
                np.concatenate(parts) if parts else np.zeros(0, np.int32)
            )
            out[: len(flat)] = flat
            out[len(flat):] = pad_val
            return len(flat)

        lane_w = 4 * b_loc + k_dn + 2 * k_sp + 2 * k_h + k_d
        slot = self._pl.acquire((n_shards, lane_w), np.int32)
        lanes = slot.buf
        n_dense = n_sparse = n_heads = n_dels = 0
        for s in range(n_shards):
            o = 0
            lanes[s, : 4 * b_loc] = counts[s].ravel()
            o = 4 * b_loc
            n_dense += fill(lanes[s, o : o + k_dn], dense[s], NULL)
            o += k_dn
            n_sparse += fill(lanes[s, o : o + k_sp], sp_r[s], oob_r)
            fill(lanes[s, o + k_sp : o + 2 * k_sp], sp_v[s], NULL)
            o += 2 * k_sp
            n_heads += fill(lanes[s, o : o + k_h], hd_s[s], oob_s)
            fill(lanes[s, o + k_h : o + 2 * k_h], hd_v[s], NULL)
            o += 2 * k_h
            n_dels += fill(lanes[s, o : o + k_d], dl_r[s], oob_r)
        stats = np.asarray([n_dense, n_sparse, n_heads, n_dels], np.int64)
        return slot, (k_dn, k_sp, k_h, k_d), stats, max_rows

    @property
    def last_flush_metrics(self) -> dict | None:
        """Host-side per-phase timers + batch stats of the newest flush —
        the compatibility view over the obs flush-history ring (the SAME
        dict object as ``obs.history.latest``; the ring keeps the last
        ``YTPU_OBS_HISTORY`` flushes)."""
        return self.obs.history.latest

    @property
    def last_metrics(self) -> dict | None:
        """Global psum'd counters from the last sharded flush (syncs)."""
        if self._metrics_dev is None:
            return None
        return {k: int(v) for k, v in self._metrics_dev.items()}

    # -- observability exposition -------------------------------------------

    def metrics_text(self) -> str:
        """Prometheus exposition-format dump of the engine registry merged
        with the process-global one (sync protocol counters)."""
        return self.obs.metrics_text()

    def metrics_snapshot(self) -> dict:
        """JSON-able snapshot: registry contents + newest flush metrics +
        the full flush-history ring."""
        return self.obs.snapshot()

    def export_chrome_trace(self) -> dict:
        """Chrome-trace JSON of recorded host spans — loadable by Perfetto
        / chrome://tracing.  Complements jax.profiler device traces."""
        return self.obs.tracer.chrome_trace()

    def save_trace(self, path: str) -> str:
        """Write export_chrome_trace() to ``path``; returns the path."""
        return self.obs.tracer.save(path)

    # -- exports ------------------------------------------------------------

    def state_vector(self, doc: int) -> dict[int, int]:
        fb = self.fallback.get(doc)
        if fb is not None:
            from ..core import get_state_vector

            return {c: v for c, v in get_state_vector(fb.store).items()}
        return self.mirrors[doc].state_vector()

    def _order(self, doc: int, seg: int) -> tuple[np.ndarray, np.ndarray]:
        """Segment-order row ids + deleted flags for one doc's segment.

        Host path (default): walk the planner's linked list — no device
        round trip (the r2 "per-doc dispatches in exports" weakness).
        Device path (export_from_device): rank the doc's resident right
        links with the pointer-doubling kernel and read back — exports
        then PROVE the device state, which is how the test suite runs.
        """
        m = self.mirrors[doc]
        if not self.export_from_device:
            rows_l: list = []
            dele_l: list = []
            host_deleted = m._host_deleted_rows
            nxt = m.list_next
            r = m.head_of_seg[seg] if seg < len(m.head_of_seg) else NULL
            while r != NULL:
                r = int(r)
                rows_l.append(r)
                dele_l.append(r in host_deleted)
                r = nxt[r]
            return np.asarray(rows_l, np.int64), np.asarray(dele_l, bool)
        if self._right is None:
            return np.zeros(0, np.int64), np.zeros(0, bool)
        self._apply_pending_hydrations()  # device read-back must see them
        valid_host = np.zeros(self._right.shape[1], bool)
        n = m.n_rows
        if n:
            valid_host[:n] = np.asarray(m.row_seg[:n], np.int32) == seg
        d = np.asarray(
            kernels.list_ranks(
                self._right[doc : doc + 1], self._put_r(valid_host[None])
            )
        )[0]
        deleted = np.asarray(self._deleted)[doc]
        rows = np.nonzero(d >= 0)[0]
        # larger distance-to-tail = earlier in the document
        rows = rows[np.argsort(-d[rows], kind="stable")]
        return rows, deleted[rows]

    def rows_in_order(
        self, doc: int, name: str | None = None
    ) -> list[tuple[int, int, int, bool]]:
        """(client, clock, length, deleted) per row in list order of one root
        type — the convergence-oracle view (mirrors compare_struct_stores)."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            out = []
            item = fb.get_text(name)._start
            while item is not None:
                out.append((item.id.client, item.id.clock, item.length, item.deleted))
                item = item.right
            return out
        m = self.mirrors[doc]
        seg = m.segments.get((name, None, NULL))
        if seg is None:
            return []
        rows, dels = self._order(doc, seg)
        return [
            (
                m.client_of_slot[m.row_slot[r]],
                m.row_clock[r],
                m.row_len[r],
                bool(d),
            )
            for r, d in zip(rows, dels)
        ]

    def text(self, doc: int, name: str | None = None) -> str:
        """Materialize the content of one root text/list type."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            return fb.get_text(name).to_string()
        m = self.mirrors[doc]
        seg = m.segments.get((name, None, NULL))
        if seg is None:
            return ""
        rows, dels = self._order(doc, seg)
        return visible_text(m, rows, dels)

    def to_delta(
        self,
        doc: int,
        name: str | None = None,
        snapshot=None,
        prev_snapshot=None,
        compute_ychange=None,
    ) -> list:
        """Attributed rich-text delta of one root text type, straight from
        the mirror (reference YText.toDelta, YText.js:936-1030): format
        runs toggle current_attributes, strings/embeds emit insert ops —
        no CPU-doc replay needed for rich-text consumers.

        With ``snapshot`` (and optionally ``prev_snapshot``), renders the
        point-in-time / two-snapshot diff view with ``ychange``
        attribution (reference YText.js:936-1030 toDelta(snapshot,
        prevSnapshot, computeYChange)) for DEVICE-RESIDENT rooms — the
        mirror keeps deleted runs' content (engine default gc=False), so
        history renders without demoting the doc."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            return fb.get_text(name).to_delta(
                snapshot, prev_snapshot, compute_ychange
            )
        m = self.mirrors[doc]
        seg = m.segments.get((name, None, NULL))
        if seg is None:
            return []
        if snapshot is None and prev_snapshot is None:
            return self._delta_of_seg(doc, seg)
        return self._delta_of_seg_snapshot(
            doc, seg, snapshot, prev_snapshot, compute_ychange
        )

    # -- relative positions (cursors) from mirror columns -------------------

    def relative_position_from_index(self, doc: int, index: int,
                                     name: str | None = None):
        """Stable cursor for one root type of a device-resident room,
        computed from mirror columns alone — no CPU-doc materialization,
        no device round trip (reference RelativePosition.js:85-104
        createRelativePositionFromTypeIndex).  Returns a standard
        :class:`~yjs_tpu.utils.relative_position.RelativePosition`
        (encode/decode/JSON interop with JS peers applies)."""
        from ..ids import create_id
        from ..utils.relative_position import (
            RelativePosition,
            create_relative_position_from_type_index,
        )

        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            # type-agnostic root access: the mirror branch below walks
            # segment rows without caring about the root's kind, so the
            # demoted branch must too (get_text on an already-typed
            # array/xml root raises)
            return create_relative_position_from_type_index(
                fb.get(name), index
            )
        m = self.mirrors[doc]
        seg = m.segments.get((name, None, NULL))
        if seg is not None:
            rows, dels = self._order(doc, seg)
            for r, d in zip(rows, dels):
                r = int(r)
                if d or not m.row_countable[r]:
                    continue
                ln = int(m.row_len[r])
                if ln > index:
                    client = m.client_of_slot[int(m.row_slot[r])]
                    return RelativePosition(
                        None, name, create_id(client, int(m.row_clock[r]) + index)
                    )
                index -= ln
        return RelativePosition(None, name, None)

    def _row_of_id(self, m, client: int, clock: int) -> int | None:
        """Row containing (client, clock) via the mirror fragment index,
        or None when that clock is not integrated yet (reference
        getItem/findIndexSS semantics against columnar state)."""
        slot = m.slot_of_client.get(client)
        if slot is None or m.state[slot] <= clock:
            return None
        fi = m._frag_containing(slot, clock)
        return None if fi is None else int(m.frag_row[slot][fi])

    def absolute_index_from_relative(self, doc: int, rpos) -> int | None:
        """Resolve a cursor back to a list index against the room's
        CURRENT state, from mirror columns alone (reference
        RelativePosition.js:214-262
        createAbsolutePositionFromRelativePosition).  Returns None when
        the anchor is unknown (not yet integrated / garbage collected),
        exactly like the reference.

        Deviation (documented): the return value is the index alone —
        on the engine path the type handle is the (doc, root-name) pair
        the caller already holds, not a live Y type object.  ``redone``
        chains are a CPU-replica concept (the pointers are local to the
        undoing replica and never on the wire), so the mirror path has
        none to follow; rooms with server-side undo enabled resolve
        through their replica instead (see TpuProvider
        .resolve_relative_position), which runs the reference
        follow-redone walk verbatim."""
        from ..utils.relative_position import (
            create_absolute_position_from_relative_position,
        )

        fb = self.fallback.get(doc)
        if fb is not None:
            a = create_absolute_position_from_relative_position(rpos, fb)
            return None if a is None else a.index
        m = self.mirrors[doc]

        def visible_len(seg: int) -> int:
            rows, dels = self._order(doc, seg)
            tot = 0
            for r, d in zip(rows, dels):
                r = int(r)
                if not d and m.row_countable[r]:
                    tot += int(m.row_len[r])
            return tot

        if rpos.item is not None:
            r = self._row_of_id(m, rpos.item.client, rpos.item.clock)
            if r is None or m.row_is_gc[r]:
                # unknown clock or GC'd anchor: reference returns null
                # (followRedone landed on a GC struct)
                return None
            seg = int(m.row_seg[r])
            _name, _sub, parent = m.seg_info[seg]
            if parent != NULL and parent in m._host_deleted_rows:
                # parent type deleted: reference keeps index 0
                return 0
            deleted = r in m._host_deleted_rows
            index = (
                0
                if (deleted or not m.row_countable[r])
                else rpos.item.clock - int(m.row_clock[r])
            )
            rows, dels = self._order(doc, seg)
            for rr, dd in zip(rows, dels):
                rr = int(rr)
                if rr == r:
                    return index
                if not dd and m.row_countable[rr]:
                    index += int(m.row_len[rr])
            return None  # anchor row not reachable in its segment
        if rpos.tname is not None:
            seg = m.segments.get((rpos.tname, None, NULL))
            # absent root = empty type (reference doc.get(tname)._length)
            return 0 if seg is None else visible_len(seg)
        if rpos.type is not None:
            r = self._row_of_id(m, rpos.type.client, rpos.type.clock)
            if r is None or m.row_is_gc[r] or int(m.row_content_ref[r]) != 7:
                return None
            seg = m.segments.get((None, None, r))
            return 0 if seg is None else visible_len(seg)
        raise ValueError("invalid relative position")

    def snapshot(self, doc: int):
        """Point-in-time capture (state vector + delete set) of one room,
        straight from the mirror — no CPU-doc materialization, no device
        round trip (reference Snapshot.js:118-121 snapshot()).  The
        result is a standard :class:`~yjs_tpu.utils.snapshot.Snapshot`:
        encode/decode/equality and createDocFromSnapshot interop apply."""
        from ..utils.snapshot import create_snapshot
        from ..utils.snapshot import snapshot as cpu_snapshot

        fb = self.fallback.get(doc)
        if fb is not None:
            return cpu_snapshot(fb)
        m = self.mirrors[doc]
        return create_snapshot(m.delete_set(), m.state_vector())

    def create_doc_from_snapshot(self, doc: int, snap, new_doc=None) -> Doc:
        """Rewind one room to ``snap`` as a standalone CPU :class:`Doc`
        (reference Snapshot.js:162-202 createDocFromSnapshot).  The room
        itself stays device-resident and untouched; the engine's full
        state is materialized host-side (gc=False history is retained by
        default) and truncated to the snapshot."""
        from ..updates import apply_update
        from ..utils.snapshot import create_doc_from_snapshot as _cdfs

        fb = self.fallback.get(doc)
        if fb is not None:
            return _cdfs(fb, snap, new_doc)
        if self.gc:
            raise RuntimeError("originDoc must not be garbage collected")
        origin = Doc(gc=False)
        apply_update(origin, self.encode_state_as_update(doc))
        return _cdfs(origin, snap, new_doc)

    def _delta_of_seg_snapshot(self, doc, seg, snap, prev, compute_ychange):
        """Snapshot-scoped delta from the mirror columns: each run is cut
        into element sub-ranges of uniform visibility under (sv, ds) of
        both snapshots, so no struct pre-splitting is needed — the exact
        twin of YText.js:936-1030 / types/ytext.py to_delta (the parity
        test pins them op-for-op)."""
        from bisect import bisect_right

        from ..core import ContentEmbed, ContentFormat, ContentString, is_deleted
        from ..ids import create_id
        from ..types.ytext import update_current_attributes

        if self.gc:
            # compaction GC'd deleted runs' content: historical views are
            # unrenderable, exactly like the reference's
            # createDocFromSnapshot guard (Snapshot.js:165)
            raise RuntimeError(
                "snapshot-scoped to_delta requires engine gc=False"
            )
        m = self.mirrors[doc]
        ops: list = []
        cur: dict = {}
        parts: list[str] = []
        # per-snapshot, per-client sorted (start, end) edge tables: the
        # row loop bisects instead of scanning the whole DeleteSet
        edge_tables: dict[int, dict[int, tuple[list, list]]] = {}
        for si, sn in enumerate((snap, prev)):
            if sn is None:
                continue
            tab: dict[int, tuple[list, list]] = {}
            for cl, items in sn.ds.clients.items():
                tab[cl] = (
                    [it.clock for it in items],
                    [it.clock + it.len for it in items],
                )
            edge_tables[si] = tab

        def pack_str():
            if parts:
                op = {"insert": from_u16("".join(parts))}
                if cur:
                    op["attributes"] = dict(cur)
                ops.append(op)
                parts.clear()

        def vis(sn, client, clk):
            # element-level twin of Snapshot.js:133-135 isVisible (the
            # reference checks post-split item starts; elements subsume)
            if sn is None:
                return None
            return (
                client in sn.sv
                and sn.sv.get(client, 0) > clk
                and not is_deleted(sn.ds, create_id(client, clk))
            )

        rows, dels = self._order(doc, seg)
        for r, dl in zip(rows, dels):
            r = int(r)
            if m.row_is_gc[r]:
                continue  # GC'd runs carry no content; see gc caveat
            client = m.client_of_slot[m.row_slot[r]]
            clock = int(m.row_clock[r])
            ln = int(m.row_len[r])
            # visibility boundaries inside this run: sv bounds + ds edges
            # (bisected — ds lists are sorted and disjoint)
            cuts = {clock, clock + ln}
            for si, sn in enumerate((snap, prev)):
                if sn is None:
                    continue
                b = sn.sv.get(client, 0)
                if clock < b < clock + ln:
                    cuts.add(b)
                starts_ends = edge_tables[si].get(client)
                if starts_ends is None:
                    continue
                starts, ends = starts_ends
                j = bisect_right(ends, clock)
                while j < len(starts) and starts[j] < clock + ln:
                    if clock < starts[j]:
                        cuts.add(starts[j])
                    if ends[j] < clock + ln:
                        cuts.add(ends[j])
                    j += 1
            content = None
            pts = sorted(cuts)
            for a, b in zip(pts, pts[1:]):
                v_now = vis(snap, client, a)
                if snap is None:
                    v_now = not dl  # plain visibility when only prev given
                v_prev = vis(prev, client, a)
                if not (v_now or (prev is not None and v_prev)):
                    continue
                if content is None:
                    content = m.realized_content(r)
                if isinstance(content, ContentString):
                    cy = cur.get("ychange")
                    if snap is not None and not v_now:
                        if (
                            cy is None
                            or cy.get("user") != client
                            or cy.get("state") != "removed"
                        ):
                            pack_str()
                            cur["ychange"] = (
                                compute_ychange("removed", create_id(client, a))
                                if compute_ychange
                                else {"type": "removed"}
                            )
                    elif prev is not None and not v_prev:
                        if (
                            cy is None
                            or cy.get("user") != client
                            or cy.get("state") != "added"
                        ):
                            pack_str()
                            cur["ychange"] = (
                                compute_ychange("added", create_id(client, a))
                                if compute_ychange
                                else {"type": "added"}
                            )
                    elif cy is not None:
                        pack_str()
                        cur.pop("ychange", None)
                    parts.append(content.str[a - clock : b - clock])
                elif isinstance(content, ContentEmbed):
                    pack_str()
                    op = {"insert": content.embed}
                    if cur:
                        op["attributes"] = dict(cur)
                    ops.append(op)
                elif isinstance(content, ContentFormat):
                    if v_now:
                        pack_str()
                        update_current_attributes(cur, content)
        pack_str()
        return ops

    def _delta_of_seg(self, doc: int, seg: int) -> list:
        from ..core import ContentEmbed, ContentFormat, ContentString
        from ..types.ytext import update_current_attributes

        m = self.mirrors[doc]
        ops: list = []
        cur: dict = {}
        parts: list[str] = []

        def pack_str():
            if parts:
                op = {"insert": from_u16("".join(parts))}
                if cur:
                    op["attributes"] = dict(cur)
                ops.append(op)
                parts.clear()

        rows, dels = self._order(doc, seg)
        for r, dl in zip(rows, dels):
            if dl:
                continue
            c = m.realized_content(int(r))
            if isinstance(c, ContentString):
                parts.append(c.str)
            elif isinstance(c, ContentEmbed):
                pack_str()
                op = {"insert": c.embed}
                if cur:
                    op["attributes"] = dict(cur)
                ops.append(op)
            elif isinstance(c, ContentFormat):
                pack_str()
                update_current_attributes(cur, c)
        pack_str()
        return ops

    def xml_string(self, doc: int, name: str | None = None) -> str:
        """Serialize a root XML fragment from the mirror (reference
        YXmlFragment/YXmlElement/YXmlText toString — sorted attributes,
        nested formatting tags), no CPU-doc replay."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            from ..types.yxml import YXmlHook

            def render(t):
                # YXmlHook inherits YMap and has no serialization in the
                # reference; emit the same stable "" as the mirror path
                if isinstance(t, YXmlHook):
                    return ""
                return t.to_string()

            frag = fb.get_xml_fragment(name)
            return "".join(render(t) for t in frag.to_array())
        seg = self.mirrors[doc].segments.get((name, None, NULL))
        if seg is None:
            return ""
        return self._xml_children(doc, seg)

    def _xml_children(self, doc: int, seg: int) -> str:
        m = self.mirrors[doc]
        rows, dels = self._order(doc, seg)
        parts: list[str] = []
        for r, dl in zip(rows, dels):
            r = int(r)
            if dl or not m.row_countable[r]:
                continue
            c = m.realized_content(r)
            if getattr(c, "REF", None) == 7:
                parts.append(self._xml_node(doc, r, c))
            else:
                parts.extend(str(v) for v in c.get_content())
        return "".join(parts)

    def _xml_node(self, doc: int, row: int, content) -> str:
        m = self.mirrors[doc]
        t = content.type
        kind = type(t).__name__
        child_seg = m.segments.get((None, None, row))
        if kind == "YXmlElement":
            # sorted-attribute serialization (reference YXmlElement.js:97-113)
            attrs = self._map_json_of(doc, None, row)
            attrs_string = " ".join(
                f'{key}="{attrs[key]}"' for key in sorted(attrs.keys())
            )
            node_name = t.node_name.lower()
            inner = (
                self._xml_children(doc, child_seg)
                if child_seg is not None
                else ""
            )
            sep = " " + attrs_string if attrs_string else ""
            return f"<{node_name}{sep}>{inner}</{node_name}>"
        if kind == "YXmlText":
            # delta attributes as nested sorted tags (YXmlText.js:65-97)
            if child_seg is None:
                return ""
            out = []
            for delta in self._delta_of_seg(doc, child_seg):
                names = sorted(delta.get("attributes", {}))
                s = ""
                for node_name in names:
                    s += f"<{node_name}"
                    a = delta["attributes"][node_name]
                    for key in sorted(a):
                        s += f' {key}="{a[key]}"'
                    s += ">"
                s += str(delta["insert"])
                for node_name in reversed(names):
                    s += f"</{node_name}>"
                out.append(s)
            return "".join(out)
        if kind == "YXmlFragment":
            return (
                self._xml_children(doc, child_seg)
                if child_seg is not None
                else ""
            )
        # YXmlHook: a YMap with a hook name — the reference's toString
        # falls through Object.prototype; serialize it as the stable empty
        # form on BOTH paths (the CPU fallback goes through xml_string's
        # own renderer below, so modes agree)
        return ""

    def map_json(self, doc: int, name: str | None = None) -> dict:
        """The visible {key: value} content of one root YMap (LWW winners,
        reference typeMapGet / YMap.toJSON); nested shared types render
        recursively (dicts / lists / strings)."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            return fb.get_map(name).to_json()
        return self._map_json_of(doc, name, NULL)

    def _map_json_of(self, doc: int, name: str | None, parent_row: int) -> dict:
        m = self.mirrors[doc]
        if parent_row != NULL:
            # nested: the reverse index lists exactly this type's segments
            segs = [
                (m.seg_info[s][1], s)
                for s in m._segs_of_parent.get(parent_row, ())
                if m.seg_info[s][1] is not None
            ]
        else:
            segs = [
                (sub, seg)
                for (n, sub, p), seg in m.segments.items()
                if n == name and sub is not None and p == NULL
            ]
        out = {}
        for sub, seg in segs:
            # the map-key chain is a device segment like any list: its
            # visible value is the last undeleted entry of the chain in
            # list order (LWW keeps only the final tail undeleted)
            rows, dels = self._order(doc, seg)
            if not len(rows) or dels[-1]:
                continue
            out[sub] = self._value_of_row(doc, int(rows[-1]))
        return out

    def _value_of_row(self, doc: int, row: int):
        """A row's visible value (reference typeMapGet: the last content
        element), recursing into nested shared types."""
        m = self.mirrors[doc]
        content = m.realized_content(row)
        if getattr(content, "REF", None) == 7:
            return self._type_json(doc, row)
        return content.get_content()[-1]

    def _list_json(self, doc: int, seg: int) -> list:
        """One list segment's visible values in document order, recursing
        into nested shared types (reference YArray.toJSON)."""
        m = self.mirrors[doc]
        rows, dels = self._order(doc, seg)
        out = []
        for r, dl in zip(rows, dels):
            if dl or not m.row_countable[r]:
                continue
            content = m.realized_content(r)
            if getattr(content, "REF", None) == 7:
                out.append(self._type_json(doc, r))
            else:
                out.extend(content.get_content())
        return out

    def _type_json(self, doc: int, row: int):
        """Materialize a nested shared type held by ``row``'s ContentType:
        maps render as dicts, text as strings, lists as JSON arrays
        (reference YMap/YText/YArray .toJSON)."""
        m = self.mirrors[doc]
        kind = type(m.realized_content(row).type).__name__
        if kind in ("YMap", "YXmlHook"):
            return self._map_json_of(doc, None, row)
        seg = m.segments.get((None, None, row))
        if seg is None:
            return "" if kind in ("YText", "YXmlText") else []
        if kind in ("YText", "YXmlText"):
            rows, dels = self._order(doc, seg)
            return visible_text(m, rows, dels)
        return self._list_json(doc, seg)

    def to_json(self, doc: int, name: str | None = None):
        """A root YArray's JSON content, nested types included
        (reference YArray.toJSON)."""
        name = name or self.root_name
        fb = self.fallback.get(doc)
        if fb is not None:
            return fb.get_array(name).to_json()
        seg = self.mirrors[doc].segments.get((name, None, NULL))
        if seg is None:
            return []
        return self._list_json(doc, seg)

    def encode_state_vector(self, doc: int) -> bytes:
        fb = self.fallback.get(doc)
        if fb is not None:
            from ..updates import encode_state_vector

            return encode_state_vector(fb)
        return self.mirrors[doc].encode_state_vector()

    def encode_state_as_update(
        self, doc: int, encoded_target_sv: bytes | None = None, v2: bool = False
    ) -> bytes:
        """Sync step 2 straight from the columnar mirror (no CPU Doc)."""
        fb = self.fallback.get(doc)
        if fb is not None:
            from ..updates import encode_state_as_update, encode_state_as_update_v2

            f = encode_state_as_update_v2 if v2 else encode_state_as_update
            return f(fb, encoded_target_sv)
        target = None
        if encoded_target_sv is not None:
            from ..updates import decode_state_vector

            target = decode_state_vector(encoded_target_sv)
        return self.mirrors[doc].encode_state_as_update(target, v2=v2)

    # -- batched sync kernels ----------------------------------------------

    def _sync_columns(self, docs: list[int]):
        """Stacked (row_slot, row_clock, row_end) columns for a doc subset,
        padded to the widest doc (NULL rows are masked by the kernels).
        Served from each mirror's cached numpy columns."""
        n = max((self.mirrors[i].n_rows for i in docs), default=0)
        n = max(n, 1)
        k = len(docs)
        row_slot = np.full((k, n), NULL, np.int32)
        row_clock = np.zeros((k, n), np.int32)
        row_end = np.zeros((k, n), np.int32)
        for j, i in enumerate(docs):
            m = self.mirrors[i]
            r = m.n_rows
            if r:
                c = m._np_cols()
                row_slot[j, :r] = c["slot"]
                row_clock[j, :r] = c["clock"]
                row_end[j, :r] = c["row_end"]
        return row_slot, row_clock, row_end

    def state_vectors_batched(self, docs: list[int]) -> list[dict[int, int]]:
        """State vectors for many docs in ONE ``state_vector_kernel``
        dispatch (the segment-max of StructStore.getStateVector batched
        over the doc axis — SURVEY.md §2 sync-protocol row).  Results align
        positionally with ``docs``; fallback docs are served by the CPU
        core."""
        out: list[dict[int, int] | None] = [None] * len(docs)
        dev = [(j, i) for j, i in enumerate(docs) if i not in self.fallback]
        for j, i in enumerate(docs):
            if i in self.fallback:
                out[j] = self.state_vector(i)
        if dev:
            dev_docs = [i for _, i in dev]
            row_slot, _clock, row_end = self._sync_columns(dev_docs)
            # bucket n_slots so client-count growth compiles O(log) variants
            n_slots = _bucket(
                max(1, max(len(self.mirrors[i].client_of_slot) for i in dev_docs)),
                4,
            )
            if self.mesh is not None:
                # the sharded segment-max path: pad the doc subset to the
                # mesh axis, compute shard-locally, gather over ICI
                axis = self.mesh.axis_names[0]
                size = self.mesh.shape[axis]
                pad = (-len(dev_docs)) % size
                if pad:
                    row_slot = np.pad(
                        row_slot, ((0, pad), (0, 0)), constant_values=NULL
                    )
                    row_end = np.pad(row_end, ((0, pad), (0, 0)))
                f = self._sharded_sv.get(n_slots)
                if f is None:
                    from ..parallel.mesh import sharded_state_vectors

                    f = sharded_state_vectors(self.mesh, n_slots, axis)
                    self._sharded_sv[n_slots] = f
                sv = np.asarray(
                    f(self._put_b(row_slot), self._put_b(row_end))
                )
            else:
                sv = np.asarray(
                    kernels.state_vector_kernel(
                        jnp.asarray(row_slot), jnp.asarray(row_end), n_slots
                    )
                )
            for r, (j, i) in enumerate(dev):
                m = self.mirrors[i]
                out[j] = {
                    m.client_of_slot[s]: int(sv[r, s])
                    for s in range(len(m.client_of_slot))
                    if sv[r, s] > 0
                }
        return out

    def sync_step2_batch(
        self, requests: list[tuple[int, dict[int, int] | None]], v2: bool = False
    ) -> list[bytes]:
        """Answer many sync-step-1 requests with ONE ``diff_mask_kernel``
        dispatch: (doc, remote state vector) pairs in, diff updates out
        (reference encodeStateAsUpdate, encoding.js:490-526, batched over
        the doc axis).  Fallback docs are served by the CPU core."""
        replies: list[bytes | None] = [None] * len(requests)
        dev = [
            (j, i, sv) for j, (i, sv) in enumerate(requests) if i not in self.fallback
        ]
        for j, (i, sv) in enumerate(requests):
            if i in self.fallback:
                enc_sv = None
                if sv:
                    from ..coding import DSEncoderV1
                    from ..updates import write_state_vector

                    e = DSEncoderV1()
                    write_state_vector(e, sv)
                    enc_sv = e.to_bytes()
                replies[j] = self.encode_state_as_update(i, enc_sv, v2=v2)
        # native mirrors answer straight from the C++ columns: one
        # ymx_encode_diff(_v2) call per request, no device round trip (the
        # device diff kernel still serves Python-mirror engines and can be
        # forced with YTPU_SYNC_DEVICE=1)
        if not os.environ.get("YTPU_SYNC_DEVICE"):
            rest = []
            for j, i, sv in dev:
                m = self.mirrors[i]
                enc = getattr(m, "encode_diff_update", None)
                u = enc(sv, v2=v2) if enc is not None else None
                if u is None:
                    rest.append((j, i, sv))
                else:
                    replies[j] = u
            dev = rest
        if dev:
            docs = [i for _, i, _ in dev]
            row_slot, row_clock, row_end = self._sync_columns(docs)
            n_slots = max(1, max(len(self.mirrors[i].client_of_slot) for i in docs))
            sv_dense = np.zeros((len(dev), n_slots), np.int32)
            for r, (_j, i, sv) in enumerate(dev):
                m = self.mirrors[i]
                for client, clock in (sv or {}).items():
                    s = m.slot_of_client.get(client)
                    if s is not None:
                        sv_dense[r, s] = clock
            needed, offset = kernels.diff_mask_kernel(
                self._put_r(row_slot),
                self._put_r(row_clock),
                self._put_r(row_end),
                self._put_r(sv_dense),
            )
            needed = np.asarray(needed)
            offset = np.asarray(offset)
            for r, (j, i, _sv) in enumerate(dev):
                replies[j] = self.mirrors[i].encode_masked_update(
                    needed[r], offset[r], v2=v2
                )
        return replies

    def encode_states_batched(
        self, docs: list[int], v2: bool = False
    ) -> list[bytes]:
        """Full-state exports for many docs in ONE batched dispatch (a
        sync-step-2 answer against the empty state vector) — the WAL
        checkpoint's snapshot producer (ISSUE 3): compacting a fleet
        must not cost one device round trip per doc."""
        return self.sync_step2_batch([(i, None) for i in docs], v2=v2)

    def has_pending(self, doc: int) -> bool:
        if doc in self.fallback:
            fb = self.fallback[doc]
            return bool(fb.store.pending_clients_struct_refs) or bool(
                fb.store.pending_delete_readers
            )
        return self.mirrors[doc].has_pending()
