"""Doc-free batch update ops over struct-of-arrays columns.

The SURVEY version caveat (SURVEY.md "Version caveat") requires first-class
``mergeUpdates`` / ``diffUpdate``-style batch APIs even though v13.4.9
lacks them.  ``yjs_tpu.updates`` provides the semantic oracle by replaying
into a scratch :class:`~yjs_tpu.core.Doc`; the versions here run the same
contract through the columnar pipeline instead — native wire decode,
host causal schedule, native wire encode — touching no ``Doc``, no
``Item`` objects, and no payload bytes (zero-copy ranges) — measured
1.4-3x faster than the scratch-doc oracle depending on conflict density,
and the natural building block for server-side update laundering at
engine scale.

Semantics match the oracle exactly: updates are commutative/idempotent,
causally-incomplete structs are withheld from the output (the scratch-doc
oracle parks them in pending buffers and full-state encode skips them
too), and the DS section is the merged union.  Updates embedding
subdocuments (ContentDoc) fall back to the scratch-doc oracle internally
— same result, doc-level speed — mirroring the engine's gating seam.
"""

from __future__ import annotations

from ..obs.prof import host_timed
from .columns import DocMirror, UnsupportedUpdate


def _loaded_mirror(updates: list[bytes], v2: bool):
    from .native_mirror import NativeMirror, native_plan_available

    m = NativeMirror("") if native_plan_available() else DocMirror("")
    for u in updates:
        m.ingest(u, v2)
    m.prepare_step(want_levels=False)
    return m


@host_timed("merge_updates")
def merge_updates_columnar(
    updates: list[bytes], v2: bool = False, out_v2: bool | None = None
) -> bytes:
    """Merge concurrent updates into one equivalent update, column-wise
    (the doc-free twin of :func:`yjs_tpu.updates.merge_updates`).

    ``v2`` selects the INPUT wire format; ``out_v2`` the output (defaults
    to the input format).  Mixing formats converts in one pass.
    """
    ov2 = v2 if out_v2 is None else out_v2
    try:
        m = _loaded_mirror(updates, v2)
    except UnsupportedUpdate:  # subdocuments: scratch-doc oracle
        from ..updates import convert_update_format, merge_updates

        merged = merge_updates(updates, v2=v2)
        return convert_update_format(merged, v2, ov2) if ov2 != v2 else merged
    return m.encode_state_as_update(v2=ov2)


@host_timed("diff_update")
def diff_update_columnar(
    update: bytes, encoded_state_vector: bytes, v2: bool = False
) -> bytes:
    """What a peer at ``encoded_state_vector`` is missing from ``update``
    (the doc-free twin of :func:`yjs_tpu.updates.diff_update`)."""
    from ..updates import decode_state_vector

    try:
        m = _loaded_mirror([update], v2)
    except UnsupportedUpdate:  # subdocuments: scratch-doc oracle
        from ..updates import diff_update

        return diff_update(update, encoded_state_vector, v2=v2)
    return m.encode_state_as_update(
        decode_state_vector(encoded_state_vector), v2=v2
    )


@host_timed("encode_state_vector_from_update")
def encode_state_vector_from_update_columnar(
    update: bytes, v2: bool = False
) -> bytes:
    """The state vector an update would produce, without building a doc."""
    try:
        return _loaded_mirror([update], v2).encode_state_vector()
    except UnsupportedUpdate:  # subdocuments: scratch-doc oracle
        from ..updates import encode_state_vector_from_update

        return encode_state_vector_from_update(update, v2)
