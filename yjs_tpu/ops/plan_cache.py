"""Frontier-keyed incremental plan cache (ISSUE 9).

Planning is deterministic: a mirror that has folded the same sequence of
updates (and the same structural events — compaction, GC, hydration)
from the same seed is bit-identical to any other mirror with that
history, and preparing the same staged bytes on top of it yields a
bit-identical post-plan state.  This module keys that fact:

- every mirror carries a 16-byte **plan frontier** — a blake2b digest
  chain seeded from the root type name and folded forward on every
  successful prepare (with the staged updates' content digest) and on
  every deterministic structural event (compact/GC, hydration).
  Nondeterministic events (rollback restore, plan errors that may leave
  the core mid-step) *poison* the frontier with a random nonce, so a
  stale mirror can never alias a cached entry;
- the cache maps ``(kind, frontier, staged_digest, want_levels,
  want_sched)`` to a snapshot of the post-prepare mirror state.  A hit
  replays the snapshot onto the probing doc (native: one
  ``ymx_clone_state`` deep copy; Python: a ``copy.deepcopy``) instead of
  re-planning — the resolved left/right-origin anchors, splice lists,
  and pending queues all ride along, so cached and cold flushes are
  byte-identical by construction.

Entries are immutable once inserted and never *become* wrong (the key is
the full mutation history); eviction is pure memory policy (LRU over
``YTPU_PLAN_CACHE_CAP`` entries / ``YTPU_PLAN_CACHE_BYTES`` bytes).

Env knobs: ``YTPU_PLAN_CACHE=0`` disables probing and insertion
entirely; ``YTPU_PLAN_CACHE_CAP`` (entries, default 4096),
``YTPU_PLAN_CACHE_BYTES`` (approx. host bytes, default 1 GiB),
``YTPU_PLAN_CACHE_MAX_ENTRY`` (largest cacheable snapshot, default
256 MiB).

The metric families live on the process-global registry (the cache is
process-global, like the kernel profiler): ``ytpu_plan_cache_hits_total``,
``ytpu_plan_cache_misses_total``,
``ytpu_plan_cache_invalidations_total{reason}``,
``ytpu_plan_fastpath_structs_total`` (structs placed by the segment-
sorted fast path in ``ops/kernels.py`` / ``DocMirror.prepare_step``),
plus ``ytpu_plan_cache_entries`` / ``ytpu_plan_cache_bytes`` gauges.
"""

from __future__ import annotations

import copy
import hashlib
import os
from collections import OrderedDict

import numpy as np

from ..obs import global_registry

# -- frontier digests ---------------------------------------------------------

_DIGEST_MEMO: dict[bytes, bytes] = {}
_DIGEST_MEMO_CAP = 4096


def update_digest(u: bytes) -> bytes:
    """Content digest of one update payload, memoized per bytes object
    (broadcast workloads queue the same object thousands of times; the
    dict key reuses Python's cached bytes hash after the first probe)."""
    d = _DIGEST_MEMO.get(u)
    if d is None:
        if len(_DIGEST_MEMO) >= _DIGEST_MEMO_CAP:
            _DIGEST_MEMO.clear()
        d = hashlib.blake2b(u, digest_size=16).digest()
        _DIGEST_MEMO[u] = d
    return d


def staged_digest(incoming) -> bytes:
    """Digest of a mirror's staged ``(update, v2)`` list, order-sensitive
    (ingest order is part of the deterministic history)."""
    h = hashlib.blake2b(digest_size=16)
    for u, v2 in incoming:
        h.update(b"\x02" if v2 else b"\x01")
        h.update(update_digest(u))
    return h.digest()


def seed_frontier(root_name: str) -> bytes:
    return hashlib.blake2b(
        b"ytpu-frontier:" + root_name.encode(), digest_size=16
    ).digest()


def fold(frontier: bytes, tag: bytes, payload: bytes = b"") -> bytes:
    """Advance a frontier by one deterministic event."""
    return hashlib.blake2b(
        frontier + tag + payload, digest_size=16
    ).digest()


def poison_frontier() -> bytes:
    """A frontier no other mirror can share — used after any event whose
    resulting state is not provably a deterministic function of the
    digest chain (rollback, mid-step plan errors)."""
    from ..obs.blackbox import flight_recorder
    from ..obs.dist import current_context

    ctx = current_context()
    if ctx is not None:
        ctx.force("frontier_poisoned")
    flight_recorder().record(
        "plan_cache", "frontier_poisoned", severity="warning",
        trace=ctx.trace_hex if ctx is not None else None,
    )
    return os.urandom(16)


# -- metric families (process-global, pre-registered at import) ---------------

_reg = global_registry()
_HITS = _reg.counter(
    "ytpu_plan_cache_hits_total",
    "Plan-cache probes served by a cached post-prepare snapshot",
)
_MISSES = _reg.counter(
    "ytpu_plan_cache_misses_total",
    "Plan-cache probes that fell through to a cold plan",
)
_INVALIDATIONS = _reg.counter(
    "ytpu_plan_cache_invalidations_total",
    "Doc plan-frontier advances/poisons outside the normal prepare flow "
    "(cached anchors no longer reachable under the old key), by reason",
    labelnames=("reason",),
)
_FASTPATH = _reg.counter(
    "ytpu_plan_fastpath_structs_total",
    "Structs placed by the segment-sorted conflict-free fast path "
    "instead of the sequential YATA walk",
)
_ENTRIES_G = _reg.gauge(
    "ytpu_plan_cache_entries", "Live plan-cache entries"
)
_BYTES_G = _reg.gauge(
    "ytpu_plan_cache_bytes", "Approximate host bytes held by the plan cache"
)
# segment-planner families (ISSUE 15): the device-authoritative cold
# planner partitions every flush batch into a fast set (integrated
# straight from device-computed ranks) and a conflict residue (the only
# structs handed to the sequential YATA walk, now a fallback)
_SEG_FAST = _reg.counter(
    "ytpu_plan_segment_fast_total",
    "Structs integrated directly from segment-planner ranks (no "
    "per-struct YATA walk)",
)
_SEG_RESIDUE = _reg.counter(
    "ytpu_plan_segment_residue_total",
    "Conflict-residue structs handed to the sequential YATA fallback",
)
_SEG_CHUNKS = _reg.counter(
    "ytpu_plan_segment_chunks_total",
    "Whole-chunk segment-planner invocations (cold docs co-planned in "
    "one batched kernel call)",
)
_SEG_SNAP_SKIP = _reg.counter(
    "ytpu_plan_segment_snapshot_reuse_total",
    "Flushes that reused the per-slot sorted fragment segments as-is "
    "(monotone chained runs) instead of rebuilding the flat snapshot",
)


def note_invalidation(reason: str) -> None:
    _INVALIDATIONS.labels(reason=reason).inc()


def note_hits(n: int) -> None:
    """Count probes served without a cold plan but outside ``lookup`` —
    intra-batch members cloned from a just-planned leader mirror."""
    if n:
        _HITS.inc(n)


def note_misses(n: int) -> None:
    """Count cold plans that never went through ``lookup`` — group
    members re-planned individually after their leader failed."""
    if n:
        _MISSES.inc(n)


def note_fastpath(n: int) -> None:
    if n:
        _FASTPATH.inc(n)


def note_segment(fast: int, residue: int) -> None:
    """Per-prepare fast-set / conflict-residue partition sizes from the
    segment planner (ISSUE 15)."""
    if fast:
        _SEG_FAST.inc(fast)
    if residue:
        _SEG_RESIDUE.inc(residue)


def note_segment_chunk() -> None:
    _SEG_CHUNKS.inc()


def note_snapshot_reuse() -> None:
    _SEG_SNAP_SKIP.inc()


def enabled() -> bool:
    return os.environ.get("YTPU_PLAN_CACHE", "1") not in ("0", "false")


# -- cache entries ------------------------------------------------------------


class _NativeEntry:
    """A cloned C++ mirror handle frozen at post-prepare state, plus the
    Python-pinned update buffers its borrowed pointers reference and the
    counts row the engine's pack path needs.

    Donation safety (ISSUE 12): everything held here lives on the HOST
    — the clone, the pinned bytes, and a private copy of the counts row.
    The pipelined flush donates the leader's device column tables into
    the integrate/scatter kernels, so by the time a follower replays
    this entry those device buffers have been freed and re-used; a
    cached entry must therefore never retain a reference to any
    ``jax.Array`` the engine dispatched.  Adoption re-packs lanes from
    this host state into the engine's own staging slot."""

    kind = "native"
    __slots__ = ("lib", "h", "counts", "pins", "frontier_after", "nbytes")

    def __init__(self, lib, src_h, counts, pins, frontier_after):
        self.lib = lib
        self.h = lib.ymx_new()
        core = int(lib.ymx_clone_state(self.h, src_h))
        self.counts = np.array(counts, np.int64, copy=True)
        self.pins = dict(pins)
        self.frontier_after = frontier_after
        self.nbytes = core + sum(len(u) for u, _a in self.pins.values())

    def close(self):
        h, self.h = self.h, None
        if h:
            self.lib.ymx_free(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _PyEntry:
    """Deepcopied post-prepare DocMirror + StepPlan (the pure-Python
    planner path); hits hand back fresh deep copies."""

    kind = "py"
    __slots__ = ("mirror", "plan", "nbytes")

    def __init__(self, mirror, plan):
        self.mirror, self.plan = copy.deepcopy((mirror, plan))
        try:
            self.nbytes = int(mirror.host_nbytes())
        except Exception:
            self.nbytes = 1 << 20

    def clone(self):
        return copy.deepcopy((self.mirror, self.plan))

    def close(self):
        pass


# -- the cache ----------------------------------------------------------------


class PlanCache:
    def __init__(self):
        self._d: OrderedDict = OrderedDict()
        self._bytes = 0
        self.cap = int(os.environ.get("YTPU_PLAN_CACHE_CAP", "4096"))
        self.byte_cap = int(
            os.environ.get("YTPU_PLAN_CACHE_BYTES", str(1 << 30))
        )
        self.max_entry = int(
            os.environ.get("YTPU_PLAN_CACHE_MAX_ENTRY", str(1 << 28))
        )

    def __len__(self):
        return len(self._d)

    def lookup(self, key):
        ent = self._d.get(key)
        if ent is None:
            _MISSES.inc()
            return None
        self._d.move_to_end(key)
        _HITS.inc()
        return ent

    def _admit(self, key, ent) -> None:
        if ent.nbytes > self.max_entry:
            ent.close()
            return
        old = self._d.pop(key, None)
        if old is not None:
            self._bytes -= old.nbytes
            old.close()
        self._d[key] = ent
        self._bytes += ent.nbytes
        while self._d and (
            len(self._d) > self.cap or self._bytes > self.byte_cap
        ):
            _k, victim = self._d.popitem(last=False)
            self._bytes -= victim.nbytes
            victim.close()
        _ENTRIES_G.set(len(self._d))
        _BYTES_G.set(self._bytes)

    def insert_native(self, key, mirror, counts):
        """Snapshot a NativeMirror's post-prepare state under ``key``.
        ``mirror.plan_frontier`` has already been folded forward by
        ``_finish_prepare``, so it is the frontier a hit must adopt."""
        self._admit(
            key,
            _NativeEntry(
                mirror._lib, mirror._h, counts, mirror._py_bufs,
                mirror.plan_frontier,
            ),
        )

    def insert_py(self, key, mirror, plan):
        self._admit(key, _PyEntry(mirror, plan))

    def clear(self):
        for ent in self._d.values():
            ent.close()
        self._d.clear()
        self._bytes = 0
        _ENTRIES_G.set(0)
        _BYTES_G.set(0)

    def stats(self) -> dict:
        return {"entries": len(self._d), "bytes": self._bytes}


_CACHE: PlanCache | None = None


def get_cache() -> PlanCache | None:
    """The process-global cache, or None when YTPU_PLAN_CACHE=0 (the env
    is re-read per call so tests/benches can toggle in-process)."""
    if not enabled():
        return None
    global _CACHE
    if _CACHE is None:
        _CACHE = PlanCache()
    return _CACHE


def reset_cache() -> None:
    """Drop every entry (tests; also frees the native handles)."""
    global _CACHE
    if _CACHE is not None:
        _CACHE.clear()
    _CACHE = None
