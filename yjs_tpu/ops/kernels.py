"""JAX device kernels for the batched CRDT engine.

The reference integrates one Item at a time into a pointer-chased linked list
(reference src/structs/Item.js:403-517).  Here the same YATA semantics run as
a ``lax.scan`` over a *static* item table (the host pre-split pass guarantees
no splits are needed mid-kernel), vmapped over the document batch: each
sequential scan step integrates one item in every document of the batch, so
the TPU's parallelism is over docs while the per-doc causal chain stays
sequential — the parallelism split called out in SURVEY.md §7 ("concurrency
across docs (vmap)").

Set semantics without sets: the reference's ``itemsBeforeOrigin`` /
``conflictingItems`` (Item.js:447-470) only ever grow between clears, so they
are modelled with a per-row visit counter: a row is in ``itemsBeforeOrigin``
iff ``visit[row] >= scan_base`` and in ``conflictingItems`` iff
``visit[row] >= clear_mark``.  No O(N) clears, O(1) membership.

All row arrays carry one extra trailing scratch row (index N) that absorbs
masked scatter writes; its contents are never read meaningfully.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

NULL = -1


def _upd(arr, idx, val, cond, dummy):
    """Masked scatter: write ``val`` at ``idx`` when ``cond`` else write the
    scratch row."""
    safe_idx = jnp.where(cond, idx, dummy)
    return arr.at[safe_idx].set(jnp.where(cond, val, arr[dummy]))


def _ids_eq(s1, k1, s2, k2):
    """compare_ids on (slot, clock) columns; NULL slot == null id."""
    return (s1 == s2) & ((s1 == NULL) | (k1 == k2))


# ---------------------------------------------------------------------------
# per-doc step kernel (vmapped over the batch by `batch_step`)
# ---------------------------------------------------------------------------


def _doc_step(statics, dyn, splits, sched, delete_rows):
    """Run one integration step for a single doc.

    statics: dict of [N+1] columns (client_key u32, origin_slot/clock,
        right_slot/clock, origin_row  i32)
    dyn: (right_link[N+1], left_link[N+1], deleted[N+1], start  — i32/bool)
    splits: [S, 2] i32 (orig_row, new_row), NULL-padded, right-to-left per
        original row
    sched: [M, 3] i32 (row, left_row, right_row), NULL-padded, causal order
    delete_rows: [D] i32, NULL-padded
    """
    right_link, left_link, deleted, start = dyn
    n1 = right_link.shape[0]
    dummy = n1 - 1

    client_key = statics["client_key"]
    oslot = statics["origin_slot"]
    oclock = statics["origin_clock"]
    rslot = statics["right_slot"]
    rclock = statics["right_clock"]
    origin_row = statics["origin_row"]

    # -- split pre-pass: link surgery for host-computed run splits ----------
    # (the device half of splitItem, reference src/structs/Item.js:84-120)
    def split_body(carry, instr):
        rl, ll, dl = carry
        orig, new = instr[0], instr[1]
        valid = orig >= 0
        safe_orig = jnp.where(valid, orig, dummy)
        old_right = rl[safe_orig]
        rl = _upd(rl, new, old_right, valid, dummy)
        rl = _upd(rl, orig, new, valid, dummy)
        ll = _upd(ll, new, orig, valid, dummy)
        ll = _upd(ll, old_right, new, valid & (old_right >= 0), dummy)
        dl = _upd(dl, new, dl[safe_orig], valid, dummy)
        return (rl, ll, dl), None

    (right_link, left_link, deleted), _ = lax.scan(
        split_body, (right_link, left_link, deleted), splits
    )

    # -- integration scan ---------------------------------------------------
    def integ_body(carry, s):
        rl, ll, st, visit, counter = carry
        k, left0, right0 = s[0], s[1], s[2]
        valid = k >= 0
        safe_k = jnp.where(valid, k, dummy)
        safe_l = jnp.where(left0 >= 0, left0, dummy)
        safe_r = jnp.where(right0 >= 0, right0, dummy)

        # fast path, the negation of reference Item.js:432-434: skip the
        # conflict scan when left is null and right is the current list head,
        # or when left.right is still exactly right
        skip = jnp.where(
            left0 == NULL,
            (right0 != NULL) & (ll[safe_r] == NULL),
            rl[safe_l] == right0,
        )

        scan_base = counter
        o0 = jnp.where(
            valid & ~skip,
            jnp.where(left0 == NULL, st, rl[safe_l]),
            NULL,
        )

        def cond_fn(cs):
            o, _left, _clear, _cnt, _visit, done = cs
            return (~done) & (o != NULL) & (o != right0)

        def body_fn(cs):
            o, left, clear, cnt, visit, done = cs
            visit = visit.at[o].set(cnt)
            cnt = cnt + 1
            # case 1: same origin -> lower client id goes left
            same_origin = _ids_eq(oslot[safe_k], oclock[safe_k], oslot[o], oclock[o])
            c1_left = same_origin & (client_key[o] < client_key[safe_k])
            c1_break = same_origin & ~c1_left & _ids_eq(
                rslot[safe_k], rclock[safe_k], rslot[o], rclock[o]
            )
            # case 2: o's origin lies between this.origin and this
            orow = origin_row[o]
            has_origin = oslot[o] != NULL
            safe_orow = jnp.where(has_origin, orow, dummy)
            in_before = has_origin & (visit[safe_orow] >= scan_base)
            c2 = ~same_origin & in_before
            c2_left = c2 & ~(visit[safe_orow] >= clear)
            # case 3: unrelated item -> done
            c3_break = ~same_origin & ~in_before
            take_left = c1_left | c2_left
            left = jnp.where(take_left, o, left)
            clear = jnp.where(take_left, cnt, clear)
            done = c1_break | c3_break
            o = jnp.where(done, o, rl[o])
            return (o, left, clear, cnt, visit, done)

        o, left, _clear, counter, visit, _done = lax.while_loop(
            cond_fn,
            body_fn,
            (
                o0.astype(jnp.int32),
                left0.astype(jnp.int32),
                scan_base.astype(jnp.int32),
                counter.astype(jnp.int32),
                visit,
                jnp.bool_(False),
            ),
        )

        # splice into the list (reference Item.js:473-489, list path)
        safe_left = jnp.where(left >= 0, left, dummy)
        right2 = jnp.where(left == NULL, st, rl[safe_left])
        rl = _upd(rl, left, k, valid & (left != NULL), dummy)
        st = jnp.where(valid & (left == NULL), k, st)
        rl = _upd(rl, k, right2, valid, dummy)
        ll = _upd(ll, k, left, valid, dummy)
        ll = _upd(ll, right2, k, valid & (right2 != NULL), dummy)
        return (rl, ll, st, visit, counter), None

    visit0 = jnp.full((n1,), -1, jnp.int32)
    (right_link, left_link, start, _visit, _counter), _ = lax.scan(
        integ_body, (right_link, left_link, start, visit0, jnp.int32(0)), sched
    )

    # -- delete marking (reference DeleteSet.js readAndApplyDeleteSet tail) -
    valid_d = delete_rows >= 0
    deleted = deleted.at[jnp.where(valid_d, delete_rows, dummy)].set(
        jnp.where(valid_d, True, deleted[dummy])
    )

    return right_link, left_link, deleted, start


@functools.partial(jax.jit, donate_argnums=(1,))
def batch_step(statics, dyn, splits, sched, delete_rows):
    """vmapped integration step over the doc batch.

    All arguments are dicts/tuples of arrays with a leading doc axis [B, ...].
    """
    return jax.vmap(_doc_step)(statics, dyn, splits, sched, delete_rows)


# ---------------------------------------------------------------------------
# export / sync kernels
# ---------------------------------------------------------------------------


@jax.jit
def list_ranks(left_link, start):
    """List ranking by pointer doubling: rank[i] = #predecessors of row i in
    its doc's linked list; invalid rows get rank -1.

    left_link: [B, N+1] i32, start: [B] i32.  log2(N) rounds of gathers —
    the parallel-prefix replacement for walking `right` pointers.
    """
    b, n1 = left_link.shape
    idx = jnp.arange(n1, dtype=jnp.int32)[None, :]
    in_list = (left_link != NULL) | (idx == start[:, None])
    in_list = in_list & (idx != n1 - 1)  # scratch row is never real
    d = jnp.where(left_link != NULL, 1, 0).astype(jnp.int32)
    p = jnp.where(in_list, left_link, NULL)
    n_rounds = max(1, math.ceil(math.log2(max(2, n1))))
    for _ in range(n_rounds):
        safe_p = jnp.where(p != NULL, p, 0)
        d = d + jnp.where(p != NULL, jnp.take_along_axis(d, safe_p, axis=1), 0)
        p = jnp.where(p != NULL, jnp.take_along_axis(p, safe_p, axis=1), NULL)
    return jnp.where(in_list, d, NULL)


@functools.partial(jax.jit, static_argnums=(2,))
def state_vector_kernel(row_slot, row_end, n_slots):
    """Dense per-doc state vectors: sv[b, slot] = max(clock+len) over rows —
    the segment-max recast of getStateVector (StructStore.js:49-56).

    row_slot: [B, N] i32 (NULL for unused rows), row_end: [B, N] i32.
    """
    seg = jnp.where(row_slot >= 0, row_slot, n_slots)
    f = jax.vmap(
        lambda s, e: jax.ops.segment_max(
            e, s, num_segments=n_slots + 1, indices_are_sorted=False
        )
    )
    sv = f(seg, row_end)
    sv = jnp.maximum(sv, 0)
    return sv[:, :n_slots]


@jax.jit
def diff_mask_kernel(row_slot, row_clock, row_end, sv):
    """Rows (or row suffixes) missing from a remote state vector: the
    columnar filter of writeClientsStructs (encoding.js:94-116).

    Returns (needed[B,N] bool, offset[B,N] i32): offset>0 means the row must
    be written from that element offset (the partial-first-struct rule,
    encoding.js:71-84).
    """
    safe_slot = jnp.where(row_slot >= 0, row_slot, 0)
    remote = jnp.take_along_axis(sv, safe_slot, axis=1)
    needed = (row_slot >= 0) & (row_end > remote)
    offset = jnp.clip(remote - row_clock, 0, None)
    return needed, jnp.where(needed, offset, 0)
