"""JAX device kernels for the batched CRDT engine.

The reference integrates one Item at a time into a pointer-chased linked list
(reference src/structs/Item.js:403-517).  Here the same YATA semantics run as
a ``lax.scan`` over a *static* item table (the host pre-split pass guarantees
no splits are needed mid-kernel), vmapped over the document batch: each
sequential scan step integrates one item in every document of the batch, so
the TPU's parallelism is over docs while the per-doc causal chain stays
sequential — the parallelism split called out in SURVEY.md §7 ("concurrency
across docs (vmap)").

Set semantics without sets: the reference's ``itemsBeforeOrigin`` /
``conflictingItems`` (Item.js:447-470) only ever grow between clears, so they
are modelled with a per-row visit counter: a row is in ``itemsBeforeOrigin``
iff ``visit[row] >= scan_base`` and in ``conflictingItems`` iff
``visit[row] >= clear_mark``.  No O(N) clears, O(1) membership.

All row arrays carry one extra trailing scratch row (index N) that absorbs
masked scatter writes; its contents are never read meaningfully.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..obs.prof import profiled
from .columns import GATHER_SUCC

NULL = -1


def _upd(arr, idx, val, cond, dummy):
    """Masked scatter: write ``val`` at ``idx`` when ``cond`` else write the
    scratch row."""
    safe_idx = jnp.where(cond, idx, dummy)
    return arr.at[safe_idx].set(jnp.where(cond, val, arr[dummy]))


def _ids_eq(s1, k1, s2, k2):
    """compare_ids on (slot, clock) columns; NULL slot == null id."""
    return (s1 == s2) & ((s1 == NULL) | (k1 == k2))


# ---------------------------------------------------------------------------
# per-doc step kernel (vmapped over the batch by `batch_step`)
# ---------------------------------------------------------------------------


def _doc_step(statics, dyn, splits, sched, delete_rows):
    """Run one integration step for a single doc.

    statics: dict of [N+1] columns (client_key u32, origin_slot/clock,
        right_slot/clock, origin_row  i32)
    dyn: (right_link[N+1], deleted[N+1], starts[S+1]) — starts holds each
        segment's list head (root lists and per-map-key chains alike); no
        left-link array: the head test is starts[seg]==row and document
        order is ranked from right links alone
    splits: [S, 2] i32 (orig_row, new_row), NULL-padded, right-to-left per
        original row
    sched: [M, 4] i32 (row, left_row, right_row, seg), NULL-padded, causal
        order
    delete_rows: [D] i32, NULL-padded
    """
    right_link, deleted, starts = dyn
    n1 = right_link.shape[0]
    dummy = n1 - 1

    # -- split pre-pass: link surgery for host-computed run splits ----------
    # (the device half of splitItem, reference src/structs/Item.js:84-120)
    def split_body(carry, instr):
        rl, dl = carry
        orig, new = instr[0], instr[1]
        valid = orig >= 0
        safe_orig = jnp.where(valid, orig, dummy)
        old_right = rl[safe_orig]
        rl = _upd(rl, new, old_right, valid, dummy)
        rl = _upd(rl, orig, new, valid, dummy)
        dl = _upd(dl, new, dl[safe_orig], valid, dummy)
        return (rl, dl), None

    (right_link, deleted), _ = lax.scan(
        split_body, (right_link, deleted), splits
    )

    # -- integration scan: one item per sequential step ---------------------
    integrate_item = _make_integrate_item(statics, dummy)

    def integ_body(carry, s):
        carry = integrate_item(carry, s[0], s[1], s[2], s[3])
        return carry, None

    (right_link, starts), _ = lax.scan(
        integ_body, (right_link, starts), sched
    )

    deleted = _apply_deletes(deleted, delete_rows, dummy)
    return right_link, deleted, starts


def _make_integrate_item(statics, dummy):
    """The single-item YATA integrate (conflict scan + splice) as a carry
    transformer — shared by the sequential path and the level path's
    deferred (true-conflict) loop."""
    client_key = statics["client_key"]
    oslot = statics["origin_slot"]
    oclock = statics["origin_clock"]
    rslot = statics["right_slot"]
    rclock = statics["right_clock"]
    origin_row = statics["origin_row"]

    def integrate_item(carry, k, left0, right0, seg):
        rl, starts = carry
        n1 = rl.shape[0]
        s_dummy = starts.shape[0] - 1
        safe_seg = jnp.where(seg >= 0, seg, s_dummy)
        st = starts[safe_seg]  # this segment's list head
        # per-scan conflict sets: fresh visit marks, so no cross-scan counter
        visit = jnp.full((n1,), -1, jnp.int32)
        counter = jnp.int32(0)
        valid = k >= 0
        safe_k = jnp.where(valid, k, dummy)
        safe_l = jnp.where(left0 >= 0, left0, dummy)

        # fast path, the negation of reference Item.js:432-434: skip the
        # conflict scan when left is null and right is the current list head
        # (st == right0), or when left.right is still exactly right
        skip = jnp.where(
            left0 == NULL,
            (right0 != NULL) & (st == right0),
            rl[safe_l] == right0,
        )

        scan_base = counter
        o0 = jnp.where(
            valid & ~skip,
            jnp.where(left0 == NULL, st, rl[safe_l]),
            NULL,
        )

        def cond_fn(cs):
            o, _left, _clear, _cnt, _visit, done = cs
            return (~done) & (o != NULL) & (o != right0)

        def body_fn(cs):
            o, left, clear, cnt, visit, done = cs
            visit = visit.at[o].set(cnt)
            cnt = cnt + 1
            # case 1: same origin -> lower client id goes left
            same_origin = _ids_eq(oslot[safe_k], oclock[safe_k], oslot[o], oclock[o])
            c1_left = same_origin & (client_key[o] < client_key[safe_k])
            c1_break = same_origin & ~c1_left & _ids_eq(
                rslot[safe_k], rclock[safe_k], rslot[o], rclock[o]
            )
            # case 2: o's origin lies between this.origin and this
            orow = origin_row[o]
            has_origin = oslot[o] != NULL
            safe_orow = jnp.where(has_origin, orow, dummy)
            in_before = has_origin & (visit[safe_orow] >= scan_base)
            c2 = ~same_origin & in_before
            c2_left = c2 & ~(visit[safe_orow] >= clear)
            # case 3: unrelated item -> done
            c3_break = ~same_origin & ~in_before
            take_left = c1_left | c2_left
            left = jnp.where(take_left, o, left)
            clear = jnp.where(take_left, cnt, clear)
            done = c1_break | c3_break
            o = jnp.where(done, o, rl[o])
            return (o, left, clear, cnt, visit, done)

        o, left, _clear, counter, visit, _done = lax.while_loop(
            cond_fn,
            body_fn,
            (
                o0.astype(jnp.int32),
                left0.astype(jnp.int32),
                scan_base.astype(jnp.int32),
                counter.astype(jnp.int32),
                visit,
                jnp.bool_(False),
            ),
        )

        # splice into the list (reference Item.js:473-489)
        safe_left = jnp.where(left >= 0, left, dummy)
        right2 = jnp.where(left == NULL, st, rl[safe_left])
        rl = _upd(rl, left, k, valid & (left != NULL), dummy)
        starts = _upd(starts, safe_seg, k, valid & (left == NULL), s_dummy)
        rl = _upd(rl, k, right2, valid, dummy)
        return (rl, starts)

    return integrate_item


def _apply_deletes(deleted, delete_rows, dummy):
    # (reference DeleteSet.js readAndApplyDeleteSet tail)
    valid_d = delete_rows >= 0
    deleted = deleted.at[jnp.where(valid_d, delete_rows, dummy)].set(
        jnp.where(valid_d, True, deleted[dummy])
    )
    return deleted


def _doc_step_levels(statics, dyn, splits, lv_sched, delete_rows, scratch_base):
    """Level-parallel integration for a single doc.

    ``scratch_base`` is this doc's row count: rows beyond it are unused
    padding, used as per-lane scratch so masked bulk scatters have UNIQUE
    indices (duplicate scatter indices serialize on TPU).  The engine
    guarantees >= W spare slots and masks phantom rows at export.

    ``lv_sched`` is the 8-field schedule packed level-major, [L, W, 8]
    NULL-padded rows of (row, left, right, check, succ, seg, fb_left,
    fb_right); items in one
    dependency level (host-assigned, see StepPlan.assign_levels) have
    distinct splice gaps and already-placed deps, so every fast-path item
    in a level splices in ONE vectorized pass; items sharing a gap are
    pre-chained by the host (ascending client = YATA case-1 order,
    reference Item.js:447-455) via the ``succ`` field, and only true
    conflicts (stale pointers — concurrent edits at one position) fall
    back to the sequential YATA scan.  Collapses the per-item lax.scan of
    `_doc_step` (~#items steps) into ~#levels steps of width ~W.
    """
    right_link, deleted, starts = dyn
    n1 = right_link.shape[0]
    dummy = n1 - 1
    s_dummy = starts.shape[0] - 1

    # split pre-pass (identical to _doc_step)
    def split_body(carry, instr):
        rl, dl = carry
        orig, new = instr[0], instr[1]
        valid = orig >= 0
        safe_orig = jnp.where(valid, orig, dummy)
        old_right = rl[safe_orig]
        rl = _upd(rl, new, old_right, valid, dummy)
        rl = _upd(rl, orig, new, valid, dummy)
        dl = _upd(dl, new, dl[safe_orig], valid, dummy)
        return (rl, dl), None

    (right_link, deleted), _ = lax.scan(
        split_body, (right_link, deleted), splits
    )

    integrate_item = _make_integrate_item(statics, dummy)

    def level_body(carry, lv):
        rl, starts = carry
        k = lv[:, 0]
        l0 = lv[:, 1]  # left write target; NULL = head, NO_LEFT_WRITE = chained
        r0 = lv[:, 2]
        chk = lv[:, 3]  # shared gap left (NULL = head gap)
        succ = lv[:, 4]  # next chain member, or GATHER_SUCC = old gap successor
        seg = lv[:, 5]  # segment (root list / map-key chain) of the row
        fb_l = lv[:, 6]  # the row's ORIGINAL YATA gap, for the deferred
        fb_r = lv[:, 7]  # fallback (differs from chk/r0 on stitched chains)
        w = k.shape[0]
        mask = k >= 0
        safe_chk = jnp.where(chk >= 0, chk, dummy)
        safe_seg = jnp.where(seg >= 0, seg, s_dummy)
        st = starts[safe_seg]  # per-lane segment head

        # vectorized fast-path check across the level: the splice gap is
        # intact iff the gap-left's successor is still exactly `right`
        # (head gap: starts[seg] == r0 — covers the empty-segment r0==NULL
        # case too).  All members of one chain share (chk, r0), so a chain
        # is fast or deferred as a whole.
        fast = mask & jnp.where(chk == NULL, st == r0, rl[safe_chk] == r0)

        # bulk splice of all fast items (gaps are distinct by construction):
        # ONE scatter for both writes (rl[l0]=k for chain heads and
        # rl[k]=succ for every member; GATHER_SUCC resolves to r0 because
        # fast means rl[chk]==r0).  masked lanes write to unique scratch
        # slots — duplicate indices would serialize the scatter on TPU
        lanes = scratch_base + jnp.arange(2 * w, dtype=jnp.int32)
        succ_v = jnp.where(succ == GATHER_SUCC, r0, succ)
        cond1 = fast & (l0 >= 0)
        idx = jnp.concatenate([
            jnp.where(cond1, l0, lanes[:w]),
            jnp.where(fast, k, lanes[w:]),
        ])
        val = jnp.concatenate([
            jnp.where(cond1, k, NULL),
            jnp.where(fast, succ_v, NULL),
        ])
        rl = rl.at[idx].set(val, unique_indices=True)
        # head writes: one segment head at most per (level, seg) by
        # construction; masked lanes pile onto the scratch cell (junk)
        starts = _upd(starts, seg, k, fast & (l0 == NULL), s_dummy)

        # deferred: true conflicts run the sequential YATA scan one by one
        # with the original YATA inputs (row, gap-left, right, seg); chain
        # members are processed in ascending-client order (their index
        # order), which the conflict scan keeps correct
        pending = mask & ~fast

        def defer_cond(cs):
            pending, _carry = cs
            return jnp.any(pending)

        def defer_body(cs):
            pending, carry = cs
            j = jnp.argmax(pending)
            carry = integrate_item(carry, k[j], fb_l[j], fb_r[j], seg[j])
            return pending.at[j].set(False), carry

        _, (rl, starts) = lax.while_loop(
            defer_cond, defer_body, (pending, (rl, starts))
        )
        return (rl, starts), None

    (right_link, starts), _ = lax.scan(
        level_body,
        (right_link, starts),
        lv_sched,
    )

    deleted = _apply_deletes(deleted, delete_rows, dummy)
    return right_link, deleted, starts


@profiled("batch_step")
@functools.partial(jax.jit, donate_argnums=(1,))
def batch_step(statics, dyn, splits, sched, delete_rows):
    """vmapped per-item integration step over the doc batch.

    All arguments are dicts/tuples of arrays with a leading doc axis [B, ...].
    """
    return jax.vmap(_doc_step)(statics, dyn, splits, sched, delete_rows)


@profiled("batch_step_levels")
@functools.partial(jax.jit, donate_argnums=(1,))
def batch_step_levels(statics, dyn, splits, lv_sched, delete_rows, scratch_base):
    """vmapped level-parallel integration step (the default engine path).

    lv_sched: [B, L, W, 8] level-major sched8 schedule, NULL-padded.
    scratch_base: [B] i32 per-doc row count (see _doc_step_levels).
    """
    return jax.vmap(_doc_step_levels)(
        statics, dyn, splits, lv_sched, delete_rows, scratch_base
    )


@profiled("batch_step_levels_shared")
@functools.partial(jax.jit, donate_argnums=(1,))
def batch_step_levels_shared(
    statics, dyn, splits, lv_sched, delete_rows, scratch_base
):
    """Level-parallel step where ALL docs share one schedule + static table
    (the broadcast-replay shape: one update fanned out to a whole batch).

    statics/splits/lv_sched/delete_rows carry NO doc axis; vmap in_axes=None
    lets XLA fuse the implicit broadcast, so HBM and the host->device link
    hold ONE copy of the static columns instead of B.
    """
    return jax.vmap(
        _doc_step_levels, in_axes=(None, 0, None, None, None, 0)
    )(statics, dyn, splits, lv_sched, delete_rows, scratch_base)


# ---------------------------------------------------------------------------
# bulk apply: host-resolved final links in one scatter (the default path)
# ---------------------------------------------------------------------------


def _doc_lanes(counts, k, cap_oob):
    """Per-lane (doc, within-doc index) derived on device from per-doc
    counts — the doc-id column never crosses the host->device link.
    Lanes beyond the true total get an out-of-bounds index (dropped)."""
    b = counts.shape[0]
    cum = jnp.cumsum(counts)
    idx = jnp.arange(k, dtype=jnp.int32)
    d = jnp.searchsorted(cum, idx, side="right").astype(jnp.int32)
    d = jnp.minimum(d, b - 1)
    within = idx - (cum[d] - counts[d])
    within = jnp.where(idx < cum[b - 1], within, cap_oob)
    return d, within


@profiled("apply_plan2")
@functools.partial(
    jax.jit, static_argnums=(2, 3, 4, 5), donate_argnums=(0,)
)
def apply_plan2(dyn, lanes, k_dn, k_sp, k_h, k_d):
    """Bulk apply with device-derived indices, minimizing transfer bytes
    (the tunnel/PCIe link is the flush bottleneck, not the scatter):

    lanes layout (ONE i32 transfer):
      [cnt_dense|cnt_sparse|cnt_heads|cnt_dels]  4 x [B] per-doc counts
      [dense_v]*k_dn    full-table link loads: doc d's section i sets
                        right_link[d, i] = v (row index derived on device —
                        fresh/full flushes ship VALUES ONLY)
      [r|v]*k_sp        sparse link writes at explicit rows
      [s|v]*k_h         segment-head writes
      [r]*k_d           delete marks
    """
    return apply_lanes(dyn, lanes, k_dn, k_sp, k_h, k_d)


def apply_lanes(dyn, lanes, k_dn, k_sp, k_h, k_d):
    """The apply_plan2 body as a plain traceable function — reused by the
    sharded mesh step (each shard applies its own lanes block locally).

    ``lanes`` may arrive int16 (engines whose row/seg capacity fits —
    halves the flush transfer over tunneled links); widened on device."""
    lanes = lanes.astype(jnp.int32)
    right_link, deleted, starts = dyn
    b = right_link.shape[0]
    n1 = right_link.shape[1]
    o = 4 * b
    cnt_dn, cnt_sp = lanes[0:b], lanes[b : 2 * b]
    cnt_h, cnt_d = lanes[2 * b : 3 * b], lanes[3 * b : 4 * b]
    if k_dn:
        dense_v = lanes[o : o + k_dn]
        d, r = _doc_lanes(cnt_dn, k_dn, n1)
        right_link = right_link.at[d, r].set(
            dense_v, mode="drop", unique_indices=True
        )
    o += k_dn
    if k_sp:
        r = lanes[o : o + k_sp]
        v = lanes[o + k_sp : o + 2 * k_sp]
        d, _ = _doc_lanes(cnt_sp, k_sp, n1)
        right_link = right_link.at[d, r].set(
            v, mode="drop", unique_indices=True
        )
    o += 2 * k_sp
    if k_h:
        s = lanes[o : o + k_h]
        v = lanes[o + k_h : o + 2 * k_h]
        d, _ = _doc_lanes(cnt_h, k_h, starts.shape[1])
        starts = starts.at[d, s].set(v, mode="drop", unique_indices=True)
    o += 2 * k_h
    if k_d:
        r = lanes[o : o + k_d]
        d, _ = _doc_lanes(cnt_d, k_d, n1)
        deleted = deleted.at[d, r].set(
            True, mode="drop", unique_indices=True
        )
    return right_link, deleted, starts


@profiled("apply_plan_shared")
@functools.partial(jax.jit, static_argnums=(2, 3, 4), donate_argnums=(0,))
def apply_plan_shared(dyn, lanes, k_l, k_h, k_d):
    """Broadcast bulk apply: ONE doc's resolved deltas fanned out to every
    doc in the batch (the B4 replay shape).  Device work is the minimal
    B x K state write; XLA broadcasts the single delta copy.

    lanes: ONE i32 array — [rows|vals]*k_l links, [segs|hvals]*k_h heads,
    [dels]*k_d deletes (single transfer, see apply_plan)."""
    right_link, deleted, starts = dyn
    o = 0
    rows, vals = lanes[o : o + k_l], lanes[o + k_l : o + 2 * k_l]
    o += 2 * k_l
    segs, hvals = lanes[o : o + k_h], lanes[o + k_h : o + 2 * k_h]
    o += 2 * k_h
    dels = lanes[o : o + k_d]
    right_link = right_link.at[:, rows].set(
        jnp.broadcast_to(vals, (right_link.shape[0], k_l)),
        mode="drop",
        unique_indices=True,
    )
    starts = starts.at[:, segs].set(
        jnp.broadcast_to(hvals, (starts.shape[0], k_h)),
        mode="drop",
        unique_indices=True,
    )
    deleted = deleted.at[:, dels].set(True, mode="drop", unique_indices=True)
    return right_link, deleted, starts


@profiled("scatter_rows")
@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def scatter_rows(right, deleted, starts, idx, new_right, new_deleted,
                 new_starts):
    """Whole-row rebuild scatter: replace docs ``idx``'s link/deleted/head
    rows with freshly packed host columns (compaction rebuilds, deferred
    warm-promotion hydrations).  The resident tables are donated, so the
    rebuild updates device state in place instead of materializing a
    second B x cap copy per array — the same donation contract as the
    flush dispatch kernels (ISSUE 12)."""
    return (
        right.at[idx].set(new_right),
        deleted.at[idx].set(new_deleted),
        starts.at[idx].set(new_starts),
    )


# ---------------------------------------------------------------------------
# segment-sorted planning kernels (ISSUE 9)
# ---------------------------------------------------------------------------
# The host planner's per-struct cost is anchor resolution: three binary
# searches per ref against the per-client fragment index.  These kernels
# hoist that into sorted-segment array ops over the whole flush batch:
#
# - `plan_anchor_lookup`: ONE searchsorted over the slot-major
#   concatenated fragment index resolves every ref's origin/rightOrigin
#   candidate at once (the composed (slot, clock) key trick — per-slot
#   runs are clock-sorted, so slot*B+clock is globally sorted);
# - `plan_conflict_scan`: adjacent-ref chain detection — a ref whose
#   origin (or rightOrigin) lands inside the PREVIOUS ref's id range
#   chains onto it (typing runs, prepend runs), so its anchor is the
#   previous ref's row with no index lookup at all.  `run_id` numbers the
#   maximal chained runs (cumsum over chain breaks).
#
# Hints are *candidates*, not answers: the planner verifies containment
# against the live columns and falls back to the sequential bisect walk
# on any miss, so a wrong hint can never change placement.  Both kernels
# have NumPy twins (the default host path, YTPU_PLAN_SEGMENT=np) and
# jitted JAX versions (YTPU_PLAN_SEGMENT=jax) whose retraces/compiles the
# kernel profiler attributes like any other device kernel.


def _compose_keys(flat_slot, flat_clock, q_slot, q_clock):
    """(slot, clock) pairs -> one sortable int64 key space; invalid
    queries (slot < 0) map below every real key."""
    base = int(max(flat_clock.max() if flat_clock.size else 0,
                   q_clock.max() if q_clock.size else 0)) + 2
    flat_key = flat_slot * base + flat_clock
    q_key = np.where(q_slot >= 0, q_slot * base + q_clock, -1)
    return flat_key, q_key


@profiled("plan_anchor_lookup")
@jax.jit
def _anchor_lookup_jax(flat_key, q_key):
    return jnp.searchsorted(flat_key, q_key, side="right") - 1


def plan_anchor_lookup(flat_slot, flat_clock, q_slot, q_clock,
                       backend: str = "np"):
    """Candidate fragment-index position for each (q_slot, q_clock): the
    last fragment starting at or before the queried clock, or -1.  The
    caller must verify slot match + containment before trusting it."""
    flat_key, q_key = _compose_keys(flat_slot, flat_clock, q_slot, q_clock)
    if backend == "jax":
        return np.asarray(_anchor_lookup_jax(flat_key, q_key))
    return np.searchsorted(flat_key, q_key, side="right") - 1


@profiled("plan_conflict_scan")
@jax.jit
def _conflict_scan_jax(client, clock, length, o_client, o_clock,
                       r_client, r_clock):
    p_client, p_clock = client[:-1], clock[:-1]
    p_end = p_clock + length[:-1]
    left = (
        (o_client[1:] == p_client)
        & (o_client[1:] >= 0)
        & (o_clock[1:] >= p_clock)
        & (o_clock[1:] < p_end)
    )
    right = (
        (r_client[1:] == p_client)
        & (r_client[1:] >= 0)
        & (r_clock[1:] >= p_clock)
        & (r_clock[1:] < p_end)
    )
    pad = jnp.zeros(1, bool)
    left = jnp.concatenate([pad, left])
    right = jnp.concatenate([pad, right])
    run_id = jnp.cumsum(~(left | right))
    return left, right, run_id


def plan_conflict_scan(client, clock, length, o_client, o_clock,
                       r_client, r_clock, backend: str = "np"):
    """Chain masks over a clock-sorted flush batch: ``left[j]`` /
    ``right[j]`` mean ref j's origin / rightOrigin lies inside ref j-1's
    id range (so its anchor row IS ref j-1's row); ``run_id`` groups the
    maximal chained (conflict-free) runs."""
    if backend == "jax":
        l, r, g = _conflict_scan_jax(
            client, clock, length, o_client, o_clock, r_client, r_clock
        )
        return np.asarray(l), np.asarray(r), np.asarray(g)
    p_client, p_clock = client[:-1], clock[:-1]
    p_end = p_clock + length[:-1]
    left = np.zeros(len(client), bool)
    right = np.zeros(len(client), bool)
    left[1:] = (
        (o_client[1:] == p_client)
        & (o_client[1:] >= 0)
        & (o_clock[1:] >= p_clock)
        & (o_clock[1:] < p_end)
    )
    right[1:] = (
        (r_client[1:] == p_client)
        & (r_client[1:] >= 0)
        & (r_clock[1:] >= p_clock)
        & (r_clock[1:] < p_end)
    )
    run_id = np.cumsum(~(left | right))
    return left, right, run_id


@profiled("plan_chunk_conflict_scan")
@jax.jit
def _chunk_conflict_scan_jax(doc_id, client, clock, length, o_client,
                             o_clock, r_client, r_clock):
    p_client, p_clock = client[:-1], clock[:-1]
    p_end = p_clock + length[:-1]
    same_doc = doc_id[1:] == doc_id[:-1]
    left = (
        same_doc
        & (o_client[1:] == p_client)
        & (o_client[1:] >= 0)
        & (o_clock[1:] >= p_clock)
        & (o_clock[1:] < p_end)
    )
    right = (
        same_doc
        & (r_client[1:] == p_client)
        & (r_client[1:] >= 0)
        & (r_clock[1:] >= p_clock)
        & (r_clock[1:] < p_end)
    )
    pad = jnp.zeros(1, bool)
    left = jnp.concatenate([pad, left])
    right = jnp.concatenate([pad, right])
    run_id = jnp.cumsum(~(left | right))
    return left, right, run_id


def plan_chunk_conflict_scan(doc_id, client, clock, length, o_client,
                             o_clock, r_client, r_clock,
                             backend: str = "np"):
    """Doc-aware twin of :func:`plan_conflict_scan` for whole-chunk
    planning (ISSUE 15): one scan over the doc-major concatenation of
    every cold doc's flush batch.  ``doc_id`` breaks chains at doc
    boundaries so a run can never span two documents — the rest of the
    semantics match the per-doc kernel exactly."""
    if backend == "jax":
        l, r, g = _chunk_conflict_scan_jax(
            doc_id, client, clock, length, o_client, o_clock,
            r_client, r_clock
        )
        return np.asarray(l), np.asarray(r), np.asarray(g)
    p_client, p_clock = client[:-1], clock[:-1]
    p_end = p_clock + length[:-1]
    same_doc = doc_id[1:] == doc_id[:-1]
    left = np.zeros(len(client), bool)
    right = np.zeros(len(client), bool)
    left[1:] = (
        same_doc
        & (o_client[1:] == p_client)
        & (o_client[1:] >= 0)
        & (o_clock[1:] >= p_clock)
        & (o_clock[1:] < p_end)
    )
    right[1:] = (
        same_doc
        & (r_client[1:] == p_client)
        & (r_client[1:] >= 0)
        & (r_clock[1:] >= p_clock)
        & (r_clock[1:] < p_end)
    )
    run_id = np.cumsum(~(left | right))
    return left, right, run_id


# ---------------------------------------------------------------------------
# export / sync kernels
# ---------------------------------------------------------------------------


def list_ranks(right_link, valid):
    """Document order from right links by pointer doubling: d[i] = distance
    to the list tail; sorting valid rows by descending d gives the order.

    right_link: [B, N+1] i32; valid: [B, N+1] bool host-known membership
    (non-GC mirrored rows; scratch cells excluded).  Returns d with -1 on
    invalid rows.
    """
    b, n1 = right_link.shape
    d = jnp.where(right_link != NULL, 1, 0).astype(jnp.int32)
    p = right_link
    n_rounds = max(1, math.ceil(math.log2(max(2, n1))))

    # fori_loop rather than a Python-unrolled loop: unrolling log2(N) gather
    # rounds makes HLO size (and XLA:CPU compile time) grow superlinearly
    # with row capacity — ~80s at N=8192 on one host core, which stalled the
    # suite on wide docs.  The rolled loop compiles in constant time.
    def _round(_, dp):
        d, p = dp
        safe_p = jnp.where(p != NULL, p, 0)
        d = d + jnp.where(p != NULL, jnp.take_along_axis(d, safe_p, axis=1), 0)
        p = jnp.where(p != NULL, jnp.take_along_axis(p, safe_p, axis=1), NULL)
        return d, p

    d, _ = jax.lax.fori_loop(0, n_rounds, _round, (d, p))
    return jnp.where(valid, d, NULL)


list_ranks = profiled("list_ranks")(jax.jit(list_ranks))


@profiled("state_vector_kernel")
@functools.partial(jax.jit, static_argnums=(2,))
def state_vector_kernel(row_slot, row_end, n_slots):
    """Dense per-doc state vectors: sv[b, slot] = max(clock+len) over rows —
    the segment-max recast of getStateVector (StructStore.js:49-56).

    row_slot: [B, N] i32 (NULL for unused rows), row_end: [B, N] i32.
    """
    seg = jnp.where(row_slot >= 0, row_slot, n_slots)
    f = jax.vmap(
        lambda s, e: jax.ops.segment_max(
            e, s, num_segments=n_slots + 1, indices_are_sorted=False
        )
    )
    sv = f(seg, row_end)
    sv = jnp.maximum(sv, 0)
    return sv[:, :n_slots]


@profiled("diff_mask_kernel")
@jax.jit
def diff_mask_kernel(row_slot, row_clock, row_end, sv):
    """Rows (or row suffixes) missing from a remote state vector: the
    columnar filter of writeClientsStructs (encoding.js:94-116).

    Returns (needed[B,N] bool, offset[B,N] i32): offset>0 means the row must
    be written from that element offset (the partial-first-struct rule,
    encoding.js:71-84).
    """
    safe_slot = jnp.where(row_slot >= 0, row_slot, 0)
    remote = jnp.take_along_axis(sv, safe_slot, axis=1)
    needed = (row_slot >= 0) & (row_end > remote)
    offset = jnp.clip(remote - row_clock, 0, None)
    return needed, jnp.where(needed, offset, 0)
