"""L4 sync & update pipeline: apply/encode updates, state vectors.

Semantics match reference src/utils/encoding.js:
- writeClientsStructs / readClientsStructRefs ... :71-198
- resumeStructIntegration (dependency-stack integrator) ... :225-321
- applyUpdate(V2)/readUpdate(V2) ... :431-478
- encodeStateAsUpdate(V2) / state-vector codec ... :490-611

Plus first-class batch ops the v13.4.9 reference lacks (SURVEY.md caveat):
``merge_updates`` and ``diff_update`` — implemented doc-free so the TPU
engine can use them column-to-column.
"""

from __future__ import annotations

from .coding import (
    DSDecoderV1,
    DSDecoderV2,
    DSEncoderV1,
    DSEncoderV2,
    UpdateDecoderV1,
    UpdateDecoderV2,
    UpdateEncoderV1,
    UpdateEncoderV2,
    default_ds_decoder,
    default_ds_encoder,
    default_update_decoder,
    default_update_encoder,
)
from .core import (
    GC,
    Doc,
    Item,
    StructStore,
    Transaction,
    create_delete_set_from_struct_store,
    find_index_ss,
    get_state,
    get_state_vector,
    read_and_apply_delete_set,
    read_item_content,
    transact,
    write_delete_set,
)
from .ids import ID, create_id
from .lib0 import decoding, encoding
from .lib0.binary import BIT6, BIT7, BIT8, BITS5
from .lib0.decoding import Decoder


# ---------------------------------------------------------------------------
# Write side
# ---------------------------------------------------------------------------

def _write_structs(encoder, structs: list, client: int, clock: int) -> None:
    """Write structs of one client from `clock` on
    (reference encoding.js:71-84)."""
    start_new_structs = find_index_ss(structs, clock)
    encoding.write_var_uint(encoder.rest_encoder, len(structs) - start_new_structs)
    encoder.write_client(client)
    encoding.write_var_uint(encoder.rest_encoder, clock)
    first_struct = structs[start_new_structs]
    first_struct.write(encoder, clock - first_struct.id.clock)
    for i in range(start_new_structs + 1, len(structs)):
        structs[i].write(encoder, 0)


def write_clients_structs(encoder, store: StructStore, _sm: dict[int, int]) -> None:
    """Write all structs newer than `_sm`, clients in DESCENDING order —
    which heavily improves the conflict algorithm on the receiving side
    (reference encoding.js:94-116)."""
    sm: dict[int, int] = {}
    for client, clock in _sm.items():
        if get_state(store, client) > clock:
            sm[client] = clock
    for client in get_state_vector(store):
        if client not in _sm:
            sm[client] = 0
    encoding.write_var_uint(encoder.rest_encoder, len(sm))
    for client, clock in sorted(sm.items(), key=lambda e: -e[0]):
        _write_structs(encoder, store.clients[client], client, clock)


def write_structs_from_transaction(encoder, transaction: Transaction) -> None:
    write_clients_structs(encoder, transaction.doc.store, transaction.before_state)


# ---------------------------------------------------------------------------
# Read side
# ---------------------------------------------------------------------------

def read_clients_struct_refs(decoder, client_refs: dict, doc: Doc) -> dict:
    """Decode the flat struct stream into per-client ref arrays
    (reference encoding.js:127-198)."""
    num_of_state_updates = decoding.read_var_uint(decoder.rest_decoder)
    for _ in range(num_of_state_updates):
        number_of_structs = decoding.read_var_uint(decoder.rest_decoder)
        refs = []
        client = decoder.read_client()
        clock = decoding.read_var_uint(decoder.rest_decoder)
        client_refs[client] = refs
        for _ in range(number_of_structs):
            info = decoder.read_info()
            if (BITS5 & info) != 0:
                # an Item; whether parent info is encoded depends on the
                # presence of origin/rightOrigin
                cant_copy_parent_info = (info & (BIT7 | BIT8)) == 0
                origin = decoder.read_left_id() if (info & BIT8) == BIT8 else None
                right_origin = decoder.read_right_id() if (info & BIT7) == BIT7 else None
                if cant_copy_parent_info:
                    if decoder.read_parent_info():
                        parent = doc.get(decoder.read_string())
                    else:
                        parent = decoder.read_left_id()
                else:
                    parent = None
                parent_sub = (
                    decoder.read_string()
                    if cant_copy_parent_info and (info & BIT6) == BIT6
                    else None
                )
                struct = Item(
                    create_id(client, clock),
                    None,
                    origin,
                    None,
                    right_origin,
                    parent,
                    parent_sub,
                    read_item_content(decoder, info),
                )
                refs.append(struct)
                clock += struct.length
            else:
                ln = decoder.read_len()
                refs.append(GC(create_id(client, clock), ln))
                clock += ln
    return client_refs


def _resume_struct_integration(transaction: Transaction, store: StructStore) -> bool:
    """Iterative dependency-stack integrator (reference
    encoding.js:225-321).  A chain stalled on a missing causal dep is
    PARKED — the chained structs go back into their clients' pending
    refs and those clients retire from this pass — while integration
    continues for every other client (the reference's restStructs /
    addStackToRestSS mechanism).  Without parking, one permanently-lost
    struct (e.g. dropped on every replica) would block unrelated
    clients' structs forever and replicas could never reconverge.

    Returns True if at least one struct integrated (callers loop to a
    fixpoint so cross-client cascades drain in one apply)."""
    stack = store.pending_stack
    clients_struct_refs = store.pending_clients_struct_refs
    client_ids = sorted(clients_struct_refs.keys())
    if not client_ids and not stack:
        return False
    parked: set[int] = set()
    progressed = False

    def park_stalled(chain):
        for item in chain:
            c = item.id.client
            refs = clients_struct_refs.get(c)
            if refs is None:
                refs = clients_struct_refs[c] = {"refs": [], "i": 0}
            rest = refs["refs"][refs["i"]:]
            rest.append(item)
            rest.sort(key=lambda s: s.id.clock)
            refs["refs"] = rest
            refs["i"] = 0
            parked.add(c)
        client_ids[:] = [c for c in client_ids if c not in parked]
        stack.clear()

    def get_next_structs_target():
        if not client_ids:
            return None
        target = clients_struct_refs[client_ids[-1]]
        while len(target["refs"]) == target["i"]:
            client_ids.pop()
            if not client_ids:
                return None
            target = clients_struct_refs[client_ids[-1]]
        return target

    cur_structs_target = get_next_structs_target()
    if cur_structs_target is None and not stack:
        return False

    if stack:
        stack_head = stack.pop()
    else:
        stack_head = cur_structs_target["refs"][cur_structs_target["i"]]
        cur_structs_target["i"] += 1

    state_cache: dict[int, int] = {}
    while True:
        client = stack_head.id.client
        local_clock = state_cache.get(client)
        if local_clock is None:
            local_clock = get_state(store, client)
            state_cache[client] = local_clock
        offset = local_clock - stack_head.id.clock if stack_head.id.clock < local_clock else 0
        if stack_head.id.clock + offset != local_clock:
            # a previous struct from this client is missing: maybe a pending
            # ref with a smaller clock can fill the gap
            struct_refs = clients_struct_refs.get(client) or {"refs": [], "i": 0}
            if len(struct_refs["refs"]) != struct_refs["i"]:
                r = struct_refs["refs"][struct_refs["i"]]
                if r.id.clock < stack_head.id.clock:
                    struct_refs["refs"][struct_refs["i"]] = stack_head
                    stack_head = r
                    remaining = sorted(
                        struct_refs["refs"][struct_refs["i"]:], key=lambda s: s.id.clock
                    )
                    struct_refs["refs"] = remaining
                    struct_refs["i"] = 0
                    continue
            # the gap-filler hasn't arrived: park this chain, keep going
            park_stalled(stack + [stack_head])
            cur_structs_target = get_next_structs_target()
            if cur_structs_target is None:
                break
            stack_head = cur_structs_target["refs"][cur_structs_target["i"]]
            cur_structs_target["i"] += 1
            continue
        missing = stack_head.get_missing(transaction, store)
        if missing is None:
            if offset == 0 or offset < stack_head.length:
                stack_head.integrate(transaction, offset)
                state_cache[client] = stack_head.id.clock + stack_head.length
                progressed = True
            if stack:
                stack_head = stack.pop()
            elif (
                cur_structs_target is not None
                and cur_structs_target["i"] < len(cur_structs_target["refs"])
            ):
                stack_head = cur_structs_target["refs"][cur_structs_target["i"]]
                cur_structs_target["i"] += 1
            else:
                cur_structs_target = get_next_structs_target()
                if cur_structs_target is None:
                    break
                stack_head = cur_structs_target["refs"][cur_structs_target["i"]]
                cur_structs_target["i"] += 1
        else:
            struct_refs = clients_struct_refs.get(missing) or {"refs": [], "i": 0}
            if len(struct_refs["refs"]) == struct_refs["i"]:
                # causally depends on a not-yet-received update: park
                park_stalled(stack + [stack_head])
                cur_structs_target = get_next_structs_target()
                if cur_structs_target is None:
                    break
                stack_head = cur_structs_target["refs"][cur_structs_target["i"]]
                cur_structs_target["i"] += 1
                continue
            stack.append(stack_head)
            stack_head = struct_refs["refs"][struct_refs["i"]]
            struct_refs["i"] += 1
    # everything not parked either integrated or was fully consumed
    for c in list(clients_struct_refs):
        if c not in parked:
            del clients_struct_refs[c]
    return progressed


def try_resume_pending_delete_readers(transaction: Transaction, store: StructStore) -> None:
    pending_readers = store.pending_delete_readers
    store.pending_delete_readers = []
    for reader in pending_readers:
        read_and_apply_delete_set(reader, transaction, store)


def _merge_read_structs_into_pending_reads(store: StructStore, clients_structs_refs: dict) -> None:
    pending = store.pending_clients_struct_refs
    for client, struct_refs in clients_structs_refs.items():
        pending_refs = pending.get(client)
        if pending_refs is None:
            pending[client] = {"refs": struct_refs, "i": 0}
        else:
            merged = (
                pending_refs["refs"][pending_refs["i"]:]
                if pending_refs["i"] > 0
                else pending_refs["refs"]
            )
            merged.extend(struct_refs)
            pending_refs["i"] = 0
            pending_refs["refs"] = sorted(merged, key=lambda r: r.id.clock)


def _cleanup_pending_structs(pending_clients_struct_refs: dict) -> None:
    for client in list(pending_clients_struct_refs.keys()):
        refs = pending_clients_struct_refs[client]
        if refs["i"] == len(refs["refs"]):
            del pending_clients_struct_refs[client]
        else:
            del refs["refs"][: refs["i"]]
            refs["i"] = 0


def read_structs(decoder, transaction: Transaction, store: StructStore) -> None:
    clients_struct_refs: dict = {}
    read_clients_struct_refs(decoder, clients_struct_refs, transaction.doc)
    _merge_read_structs_into_pending_reads(store, clients_struct_refs)
    # fixpoint: each pass may integrate structs that unblock a client
    # parked in an earlier pass (the reference achieves the same by
    # recursively re-applying store.pendingStructs on progress)
    progressed = True
    while progressed and store.pending_clients_struct_refs:
        progressed = _resume_struct_integration(transaction, store)
        _cleanup_pending_structs(store.pending_clients_struct_refs)
    try_resume_pending_delete_readers(transaction, store)


# ---------------------------------------------------------------------------
# Public apply/encode API
# ---------------------------------------------------------------------------

def read_update_v2(decoder: Decoder, ydoc: Doc, transaction_origin=None, struct_decoder=None):
    if struct_decoder is None:
        struct_decoder = UpdateDecoderV2(decoder)

    def _apply(transaction):
        read_structs(struct_decoder, transaction, ydoc.store)
        read_and_apply_delete_set(struct_decoder, transaction, ydoc.store)

    transact(ydoc, _apply, transaction_origin, False)


def read_update(decoder: Decoder, ydoc: Doc, transaction_origin=None):
    read_update_v2(decoder, ydoc, transaction_origin, default_update_decoder(decoder))


def apply_update_v2(ydoc: Doc, update: bytes, transaction_origin=None, YDecoder=UpdateDecoderV2):
    decoder = Decoder(update)
    read_update_v2(decoder, ydoc, transaction_origin, YDecoder(decoder))


def apply_update(ydoc: Doc, update: bytes, transaction_origin=None):
    decoder = Decoder(update)
    read_update_v2(decoder, ydoc, transaction_origin, default_update_decoder(decoder))


def write_state_as_update(encoder, doc: Doc, target_state_vector: dict | None = None) -> None:
    write_clients_structs(encoder, doc.store, target_state_vector or {})
    write_delete_set(encoder, create_delete_set_from_struct_store(doc.store))


def encode_state_as_update_v2(doc: Doc, encoded_target_state_vector: bytes | None = None, encoder=None) -> bytes:
    if encoder is None:
        encoder = UpdateEncoderV2()
    target_sv = (
        {}
        if encoded_target_state_vector is None
        else decode_state_vector(encoded_target_state_vector)
    )
    write_state_as_update(encoder, doc, target_sv)
    return encoder.to_bytes()


def encode_state_as_update(doc: Doc, encoded_target_state_vector: bytes | None = None) -> bytes:
    return encode_state_as_update_v2(doc, encoded_target_state_vector, default_update_encoder())


def read_state_vector(decoder) -> dict[int, int]:
    ss: dict[int, int] = {}
    ss_length = decoding.read_var_uint(decoder.rest_decoder)
    for _ in range(ss_length):
        client = decoding.read_var_uint(decoder.rest_decoder)
        clock = decoding.read_var_uint(decoder.rest_decoder)
        ss[client] = clock
    return ss


def decode_state_vector_v2(decoded_state: bytes) -> dict[int, int]:
    return read_state_vector(DSDecoderV2(Decoder(decoded_state)))


def decode_state_vector(decoded_state: bytes) -> dict[int, int]:
    return read_state_vector(default_ds_decoder(Decoder(decoded_state)))


def write_state_vector(encoder, sv: dict[int, int]):
    encoding.write_var_uint(encoder.rest_encoder, len(sv))
    for client, clock in sv.items():
        encoding.write_var_uint(encoder.rest_encoder, client)
        encoding.write_var_uint(encoder.rest_encoder, clock)
    return encoder


def write_document_state_vector(encoder, doc: Doc):
    return write_state_vector(encoder, get_state_vector(doc.store))


def encode_state_vector_v2(doc: Doc, encoder=None) -> bytes:
    if encoder is None:
        encoder = DSEncoderV2()
    write_document_state_vector(encoder, doc)
    return encoder.to_bytes()


def encode_state_vector(doc: Doc) -> bytes:
    return encode_state_vector_v2(doc, default_ds_encoder())


# ---------------------------------------------------------------------------
# Validating decoder entry point (resilience seam)
# ---------------------------------------------------------------------------

class InvalidUpdate(ValueError):
    """Raised by :func:`validate_update` for bytes that cannot be decoded
    as a complete V1/V2 update (truncation, bit corruption, varint
    overflow, garbage framing).

    Distinct from :class:`yjs_tpu.ops.columns.UnsupportedUpdate`: that
    marks WELL-FORMED traffic outside the device path's scope (demote to
    the CPU core); this marks bytes no path can apply (quarantine +
    dead-letter, never integrate)."""


def validate_update(update: bytes, v2: bool = False) -> dict:
    """Structurally decode ``update`` without applying it anywhere.

    The single validation seam the resilience layer (quarantine,
    dead-letter triage, chaos suite) trusts: it walks the full struct
    section and the trailing DeleteSet exactly like integration would,
    so bytes that pass here decode on both the CPU core and the mirror
    planner.  Returns a summary ``{"clients", "structs", "ds_ranges",
    "bytes"}``; raises :class:`InvalidUpdate` on malformed input.
    """
    if not isinstance(update, (bytes, bytearray, memoryview)):
        raise InvalidUpdate(f"not a bytes payload: {type(update).__name__}")
    update = bytes(update)
    if not update:
        raise InvalidUpdate("empty update")
    # the doc-free ref scanner is the same decoder the flush planner runs
    # (native columnar scan with pure-Python arbitration on failure)
    from .ops.columns import decode_update_refs

    try:
        refs, ds = decode_update_refs(update, v2)
    except Exception as e:
        raise InvalidUpdate(f"{type(e).__name__}: {e}") from e
    return {
        "clients": len(refs),
        "structs": sum(len(rs) for rs in refs.values()),
        "ds_ranges": len(ds),
        "bytes": len(update),
    }


# ---------------------------------------------------------------------------
# Batch ops absent from the v13.4.9 reference (SURVEY.md version caveat):
# merge/diff directly on encoded updates.  The doc-level implementation here
# is the semantic oracle; the columnar engine in yjs_tpu/ops implements the
# same contract over struct-of-arrays.
# ---------------------------------------------------------------------------

def merge_updates(updates: list[bytes], v2: bool = False) -> bytes:
    """Merge several (possibly concurrent) updates into one equivalent
    update, by replaying them into a gc-disabled scratch doc and re-encoding
    full state.  Updates are commutative and idempotent, so any order works
    (reference README.md:650-652)."""
    doc = Doc(gc=False)
    for update in updates:
        if v2:
            apply_update_v2(doc, update)
        else:
            apply_update(doc, update)
    return encode_state_as_update_v2(doc) if v2 else encode_state_as_update(doc)


def merge_updates_v2(updates: list[bytes]) -> bytes:
    return merge_updates(updates, v2=True)


def diff_update(update: bytes, state_vector: bytes, v2: bool = False) -> bytes:
    """Extract from `update` only what a peer at `state_vector` is missing."""
    doc = Doc(gc=False)
    if v2:
        apply_update_v2(doc, update)
        return encode_state_as_update_v2(doc, state_vector)
    apply_update(doc, update)
    return encode_state_as_update(doc, state_vector)


def diff_update_v2(update: bytes, state_vector: bytes) -> bytes:
    return diff_update(update, state_vector, v2=True)


def encode_state_vector_from_update(update: bytes, v2: bool = False) -> bytes:
    doc = Doc(gc=False)
    if v2:
        apply_update_v2(doc, update)
    else:
        apply_update(doc, update)
    return encode_state_vector(doc)


def convert_update_format(update: bytes, from_v2: bool, to_v2: bool) -> bytes:
    """Transcode an update between V1 and V2 wire formats."""
    doc = Doc(gc=False)
    if from_v2:
        apply_update_v2(doc, update)
    else:
        apply_update(doc, update)
    return encode_state_as_update_v2(doc) if to_v2 else encode_state_as_update(doc)
