"""Project index shared by every ytpu-lint checker.

Parses each target file once, then builds the cross-file registries the
checkers consume:

- **jit registry** — every function jitted with ``jax.jit`` (decorator,
  ``functools.partial(jax.jit, …)``, or ``name = jax.jit(fn, …)``
  assignment), with its ``donate_argnums`` / ``static_argnums`` and the
  parameter names when the def is visible.  This is what lets the
  donation-aliasing and retrace checkers resolve call sites by name.
- **lock registry** — per (module, class) the attribute names bound to
  ``threading.Lock()`` / ``threading.RLock()``, plus module-level lock
  globals, for the lock-discipline checker.

Everything here is plain :mod:`ast` — no imports of the analyzed code,
so fixtures (and the repo itself) lint without JAX present.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from .model import Finding, RULE_PARSE_ERROR


def dotted_name(node) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_func_name(call: ast.Call) -> str | None:
    """Terminal dotted name of a call's callee (``kernels.batch_step``)."""
    return dotted_name(call.func)


def terminal_name(dotted: str | None) -> str | None:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def literal_int_tuple(node) -> tuple | None:
    """A literal int, or tuple/list of literal ints, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if not (
                isinstance(elt, ast.Constant) and isinstance(elt.value, int)
            ):
                return None
            out.append(elt.value)
        return tuple(out)
    return None


@dataclass
class JitInfo:
    """One jitted callable the project defines."""

    name: str                    # resolvable call-site name (terminal)
    path: str
    line: int
    donate_argnums: tuple = ()
    static_argnums: tuple = ()
    params: tuple = ()           # positional parameter names when known
    kind: str = "decorator"      # decorator | assignment | factory

    def donated_params(self) -> tuple:
        return tuple(
            self.params[i] for i in self.donate_argnums
            if i < len(self.params)
        )


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    lock_attrs: set = field(default_factory=set)     # e.g. {"_lock"}
    methods: dict = field(default_factory=dict)      # name -> FunctionDef


@dataclass
class SourceFile:
    path: str                    # repo-relative, forward slashes
    abspath: Path
    text: str
    tree: ast.AST | None
    lines: list = field(default_factory=list)
    classes: dict = field(default_factory=dict)      # name -> ClassInfo
    module_locks: set = field(default_factory=set)   # module-level lock names
    functions: dict = field(default_factory=dict)    # top-level name -> def


def _jit_spec_from_call(call: ast.Call) -> dict | None:
    """donate/static argnums when ``call`` is a jax.jit(...) or
    functools.partial(jax.jit, ...) expression, else None."""
    fname = call_func_name(call)
    term = terminal_name(fname)
    inner_is_jit = False
    if term == "jit" or fname in ("jax.jit",):
        inner_is_jit = True
    elif term == "partial" and call.args:
        first = call.args[0]
        if terminal_name(dotted_name(first)) == "jit" or (
            dotted_name(first) == "jax.jit"
        ):
            inner_is_jit = True
    if not inner_is_jit:
        return None
    spec = {"donate": (), "static": ()}
    for kw in call.keywords:
        vals = literal_int_tuple(kw.value)
        if kw.arg == "donate_argnums" and vals is not None:
            spec["donate"] = vals
        elif kw.arg == "static_argnums" and vals is not None:
            spec["static"] = vals
    return spec


def _decorator_jit_spec(dec) -> dict | None:
    """A decorator that jits the function it wraps (possibly through
    other decorators like ``@profiled(...)`` stacked above it)."""
    if isinstance(dec, ast.Call):
        return _jit_spec_from_call(dec)
    if dotted_name(dec) in ("jax.jit",) or terminal_name(
        dotted_name(dec)
    ) == "jit":
        return {"donate": (), "static": ()}
    return None


class ProjectIndex:
    """Parsed files + cross-file registries, built once per lint run."""

    def __init__(self, root: Path, paths: list[Path]):
        self.root = Path(root)
        self.files: dict[str, SourceFile] = {}
        self.parse_findings: list[Finding] = []
        self.jit_registry: dict[str, JitInfo] = {}
        # factory functions that RETURN a donated jit (call sites are
        # dynamic — recorded so checkers/docs can reason about them)
        self.jit_factories: dict[str, JitInfo] = {}
        for p in sorted(set(paths)):
            self._load(Path(p))
        for sf in self.files.values():
            self._index_file(sf)

    # -- loading -----------------------------------------------------------

    def relpath(self, p: Path) -> str:
        try:
            return p.resolve().relative_to(self.root.resolve()).as_posix()
        except ValueError:
            return p.as_posix()

    def _load(self, p: Path) -> None:
        rel = self.relpath(p)
        text = p.read_text(encoding="utf-8", errors="replace")
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            tree = None
            self.parse_findings.append(
                Finding(
                    rule=RULE_PARSE_ERROR,
                    severity="error",
                    path=rel,
                    line=e.lineno or 1,
                    message=f"file does not parse: {e.msg}",
                )
            )
        self.files[rel] = SourceFile(
            path=rel,
            abspath=p,
            text=text,
            tree=tree,
            lines=text.splitlines(),
        )

    # -- indexing ----------------------------------------------------------

    def _index_file(self, sf: SourceFile) -> None:
        if sf.tree is None:
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, node=node)
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        ci.methods[item.name] = item
                for sub in ast.walk(node):
                    tgt = _lock_assign_target(sub)
                    if tgt and tgt.startswith("self."):
                        ci.lock_attrs.add(tgt.split(".", 1)[1])
                sf.classes[node.name] = ci
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(sf, node)
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sf.functions[node.name] = node
            tgt = _lock_assign_target(node)
            if tgt and "." not in tgt:
                sf.module_locks.add(tgt)
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                spec = _jit_spec_from_call(node.value)
                if spec and (spec["donate"] or spec["static"]):
                    for t in node.targets:
                        name = terminal_name(dotted_name(t))
                        if name:
                            self.jit_registry[name] = JitInfo(
                                name=name,
                                path=sf.path,
                                line=node.lineno,
                                donate_argnums=spec["donate"],
                                static_argnums=spec["static"],
                                kind="assignment",
                            )

    def _index_function(self, sf: SourceFile, fn) -> None:
        spec = None
        for dec in fn.decorator_list:
            spec = _decorator_jit_spec(dec)
            if spec is not None:
                break
        if spec is not None:
            params = tuple(a.arg for a in fn.args.args)
            self.jit_registry[fn.name] = JitInfo(
                name=fn.name,
                path=sf.path,
                line=fn.lineno,
                donate_argnums=spec["donate"],
                static_argnums=spec["static"],
                params=params,
                kind="decorator",
            )
            return
        # factory shape: the function RETURNS jax.jit(..., donate_argnums=…)
        # (possibly wrapped, e.g. profiled("x")(jax.jit(...)))
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                for call in ast.walk(node.value):
                    if isinstance(call, ast.Call):
                        s = _jit_spec_from_call(call)
                        if s and s["donate"]:
                            self.jit_factories[fn.name] = JitInfo(
                                name=fn.name,
                                path=sf.path,
                                line=fn.lineno,
                                donate_argnums=s["donate"],
                                static_argnums=s["static"],
                                kind="factory",
                            )
                            break

    # -- queries -----------------------------------------------------------

    def read_adjacent(self, relpath: str) -> str | None:
        """Text of a non-Python project file (README.md, …) relative to
        the project root, or None when absent."""
        p = self.root / relpath
        if not p.is_file():
            return None
        return p.read_text(encoding="utf-8", errors="replace")

    def donating(self) -> dict[str, JitInfo]:
        return {
            n: j for n, j in self.jit_registry.items() if j.donate_argnums
        }


def _lock_assign_target(node) -> str | None:
    """``self._lock`` / ``_LOCK`` when node assigns a threading lock."""
    if not isinstance(node, ast.Assign):
        return None
    if not isinstance(node.value, ast.Call):
        return None
    callee = dotted_name(node.value.func)
    if terminal_name(callee) not in ("Lock", "RLock"):
        return None
    for t in node.targets:
        d = dotted_name(t)
        if d:
            return d
    return None


def iter_python_files(paths: list[Path], exclude: tuple = ()) -> list[Path]:
    """Expand files/dirs into .py files, skipping excluded path parts."""
    out = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if any(part in exclude for part in f.parts):
                    continue
                out.append(f)
        elif p.suffix == ".py":
            out.append(p)
    return out
