"""ytpu-lint: project-specific static analysis for the y-tpu codebase.

Pure-:mod:`ast` checkers for the hazard classes this project actually
ships: buffer-donation aliasing, jit retrace storms, lock discipline and
lock-ordering deadlocks, ingress/WAL/failure-path seam completeness, and
README knob/metric drift.  Front door: ``scripts/ytpu_lint.py``.
"""

from .base import Checker
from .donation import DonationChecker
from .donation import RULE as RULE_DONATION
from .drift import DriftChecker, RULE_KNOB, RULE_METRIC, live_comparison
from .locks import LockChecker, RULE_DISCIPLINE, RULE_ORDERING
from .model import (
    Baseline,
    Finding,
    RULE_BARE_SUPPRESSION,
    RULE_PARSE_ERROR,
    RULE_USELESS_SUPPRESSION,
    SEVERITIES,
    Suppression,
    parse_suppressions,
)
from .project import JitInfo, ProjectIndex, iter_python_files
from .retrace import RetraceChecker
from .retrace import RULE as RULE_RETRACE
from .runner import (
    LintResult,
    all_rules,
    default_checkers,
    render_report,
    run_lint,
)
from .seams import RULE_FORCE, RULE_TRACE, RULE_WAL_KIND, SeamChecker

__all__ = [
    "Baseline",
    "Checker",
    "DonationChecker",
    "DriftChecker",
    "Finding",
    "JitInfo",
    "LintResult",
    "LockChecker",
    "ProjectIndex",
    "RetraceChecker",
    "RULE_BARE_SUPPRESSION",
    "RULE_DISCIPLINE",
    "RULE_DONATION",
    "RULE_FORCE",
    "RULE_KNOB",
    "RULE_METRIC",
    "RULE_ORDERING",
    "RULE_PARSE_ERROR",
    "RULE_RETRACE",
    "RULE_TRACE",
    "RULE_USELESS_SUPPRESSION",
    "RULE_WAL_KIND",
    "SEVERITIES",
    "SeamChecker",
    "Suppression",
    "all_rules",
    "default_checkers",
    "iter_python_files",
    "live_comparison",
    "parse_suppressions",
    "render_report",
    "run_lint",
]
