"""retrace-hazard: call sites that feed a jitted kernel shapes (or
static argument values) that vary per call — each distinct shape/value
compiles a fresh XLA executable, the retrace storm the PR 4 profiler
only detects dynamically (``retrace_events``), after the stall already
happened.

Two statically checkable patterns:

- **RTR-shape**: an array constructed inline with a data-dependent
  length (``np.zeros(len(xs))``, ``jnp.empty(n_structs)`` where the
  size expression contains ``len(…)`` / ``….shape``) passed straight to
  a jitted callable without flowing through a bucketing helper
  (``_bucket`` / ``_bucket_lanes`` / ``shape_bucket`` / ``*pow2*`` —
  anything whose name says it quantizes).
- **RTR-static**: a ``len(…)`` / ``….shape``-derived expression passed
  at a ``static_argnums`` position — every distinct value is a separate
  compile cache entry.

The checker is deliberately under-approximate (a size that travels
through a variable is not chased); the profiler remains the dynamic
backstop — this catches the inline cases review keeps missing."""

from __future__ import annotations

import ast

from .base import Checker, iter_functions
from .project import ProjectIndex, call_func_name, terminal_name

RULE = "retrace-hazard"

ARRAY_CTORS = frozenset(
    {"zeros", "ones", "empty", "full", "arange", "asarray", "array"}
)


def _is_bucket_call(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = terminal_name(call_func_name(node)) or ""
    low = name.lower()
    return "bucket" in low or "pow2" in low or "round_up" in low.lstrip("_")


def _dynamic_size_inside(expr) -> ast.AST | None:
    """A ``len(…)`` call or ``….shape`` attribute inside ``expr`` that is
    NOT wrapped by a bucketing helper; returns the offending node."""
    def scan(node):
        if _is_bucket_call(node):
            return None  # quantized: don't descend
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "len":
            return node
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            return node
        for child in ast.iter_child_nodes(node):
            hit = scan(child)
            if hit is not None:
                return hit
        return None

    return scan(expr)


class RetraceChecker(Checker):
    name = "retrace"
    rules = {RULE: "warning"}

    def check(self, index: ProjectIndex):
        registry = index.jit_registry
        if not registry:
            return
        for sf in index.files.values():
            if sf.tree is None:
                continue
            for symbol, _cls, fn in iter_functions(sf):
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = terminal_name(call_func_name(node))
                    info = registry.get(callee)
                    if info is None or (
                        info.path == sf.path and info.line == node.lineno
                    ):
                        continue
                    yield from self._check_call(sf, symbol, node, info)

    def _check_call(self, sf, symbol, call, info):
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break  # positions beyond a splat are unknowable
            if i in info.static_argnums:
                hit = _dynamic_size_inside(arg)
                if hit is not None:
                    yield self.finding(
                        RULE,
                        sf.path,
                        hit.lineno,
                        f"dynamic value at static_argnums position {i} "
                        f"of {info.name}() — every distinct value "
                        "compiles a new executable; round it through a "
                        "bucketing helper (_bucket/_bucket_lanes) first",
                        symbol=symbol,
                        col=hit.col_offset,
                    )
                continue
            # traced position: flag inline array ctors sized by len/.shape
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Call):
                    continue
                cname = terminal_name(call_func_name(sub))
                if cname not in ARRAY_CTORS or not sub.args:
                    continue
                hit = _dynamic_size_inside(sub.args[0])
                if hit is not None:
                    yield self.finding(
                        RULE,
                        sf.path,
                        hit.lineno,
                        f"unbucketed dynamic shape fed to jitted "
                        f"{info.name}(): {cname}(…) is sized by a "
                        "per-call length — pad to a power-of-two "
                        "bucket or the kernel retraces on every "
                        "distinct size",
                        symbol=symbol,
                        col=hit.col_offset,
                    )
