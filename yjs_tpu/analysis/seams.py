"""seam-completeness: the conventions every ingress/durability/failure
seam must honor, machine-checked.

- **seam-trace** — every ingress seam (any method named
  ``receive_update`` / ``handle_sync_message``, or the cluster's
  cross-process entry points ``handle_rpc_request`` /
  ``handle_client_message``) must adopt-or-mint a
  TraceContext (a call to ``_trace_ingress`` / ``current_context`` /
  ``mint_for_update`` / ``use_context``) AND feed the SLO pipeline
  (``…slo.receive/origin/…``) — or visibly delegate to another seam
  method (``self.shards[k].receive_update(...)``), which carries both
  obligations.  Same-class private helpers called from the seam are
  searched one level deep, so a routed implementation still passes.
- **seam-wal-kind** — the module defining the WAL record kinds must map
  every ``KIND_*`` constant in ``KIND_NAMES``, and every handler module
  (``persistence/recovery.py`` by default) must reference every kind:
  adding kind 10 without teaching recovery about it fails the lint, not
  a 3 a.m. recovery.
- **seam-force-sample** — a flight-recorder ``record(...)`` at
  ``severity="warning"|"error"`` that attaches a ``trace=`` must sit in
  a function that ``.force(…)``-samples the context first; otherwise
  the one trace you need after an incident was head-sampled away.
"""

from __future__ import annotations

import ast

from .base import Checker, iter_functions
from .project import ProjectIndex, dotted_name, terminal_name

RULE_TRACE = "seam-trace"
RULE_WAL_KIND = "seam-wal-kind"
RULE_FORCE = "seam-force-sample"

INGRESS_METHODS = frozenset(
    {
        "receive_update",
        "handle_sync_message",
        # the process-native cluster's ingress seams: every frame that
        # crosses a process boundary enters through one of these
        "handle_rpc_request",
        "handle_client_message",
    }
)
TRACE_ESTABLISHERS = frozenset(
    {"_trace_ingress", "current_context", "mint_for_update", "use_context"}
)
SLO_FEEDERS = frozenset({"receive", "origin", "integrated"})
RECORD_SEVERITIES = frozenset({"warning", "error"})


def _severity_values(node) -> set:
    """Possible constant values of a ``severity=`` argument — a plain
    string, or both arms of a conditional like
    ``"warning" if count else "error"``."""
    if isinstance(node, ast.Constant):
        return {node.value}
    if isinstance(node, ast.IfExp):
        return _severity_values(node.body) | _severity_values(node.orelse)
    return set()


def _call_desc(call: ast.Call):
    """(terminal_name, receiver) for a call; receiver is the dotted
    chain of the callee's object (``"self.slo"``), ``""`` for a bare
    name, or ``"?"`` when unresolvable (subscripts, call results) —
    ``self.shards[k].receive_update`` must still read as a delegation."""
    f = call.func
    if isinstance(f, ast.Attribute):
        recv = dotted_name(f.value)
        return f.attr, recv if recv is not None else "?"
    if isinstance(f, ast.Name):
        return f.id, ""
    return None, ""


class SeamChecker(Checker):
    name = "seams"
    rules = {
        RULE_TRACE: "error",
        RULE_WAL_KIND: "error",
        RULE_FORCE: "warning",
    }

    def __init__(
        self,
        kinds_module_suffix: str = "persistence/records.py",
        handler_module_suffixes: tuple = ("persistence/recovery.py",),
    ):
        self.kinds_module_suffix = kinds_module_suffix
        self.handler_module_suffixes = tuple(handler_module_suffixes)

    def check(self, index: ProjectIndex):
        for sf in index.files.values():
            if sf.tree is None:
                continue
            for ci in sf.classes.values():
                for mname, fn in ci.methods.items():
                    if mname in INGRESS_METHODS:
                        yield from self._check_ingress(sf, ci, mname, fn)
            for symbol, _cls, fn in iter_functions(sf):
                yield from self._check_force(sf, symbol, fn)
        yield from self._check_wal_kinds(index)

    # -- seam-trace --------------------------------------------------------

    def _check_ingress(self, sf, ci, mname, fn):
        calls = self._calls_with_helpers(ci, fn)
        has_trace = any(t in TRACE_ESTABLISHERS for t, _ in calls)
        delegates = any(
            t in INGRESS_METHODS and recv not in ("", "self")
            for t, recv in calls
        )
        has_slo = any(
            t in SLO_FEEDERS and "slo" in recv.lower() for t, recv in calls
        )
        if not (has_trace or delegates):
            yield self.finding(
                RULE_TRACE,
                sf.path,
                fn.lineno,
                f"ingress seam {ci.name}.{mname} neither adopts-or-mints "
                "a TraceContext (_trace_ingress / current_context / "
                "mint_for_update) nor delegates to another seam — "
                "updates entering here are invisible to causal tracing",
                symbol=f"{ci.name}.{mname}",
            )
        if not (has_slo or delegates):
            yield self.finding(
                RULE_TRACE,
                sf.path,
                fn.lineno,
                f"ingress seam {ci.name}.{mname} does not feed the SLO "
                "convergence pipeline (slo.receive/origin) and does not "
                "delegate to a seam that does — updates entering here "
                "never count against the convergence objective",
                symbol=f"{ci.name}.{mname}",
            )

    def _calls_with_helpers(self, ci, fn) -> list:
        """(terminal, receiver) call descriptors in ``fn`` plus, one
        level deep, in any same-class private helper it calls."""
        out: list = []
        helper_names: set = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                term, recv = _call_desc(node)
                if term is None:
                    continue
                out.append((term, recv))
                if recv == "self" and term in ci.methods and (
                    term != fn.name
                ):
                    helper_names.add(term)
        for nm in helper_names:
            for node in ast.walk(ci.methods[nm]):
                if isinstance(node, ast.Call):
                    term, recv = _call_desc(node)
                    if term is not None:
                        out.append((term, recv))
        return out

    # -- seam-force-sample -------------------------------------------------

    def _check_force(self, sf, symbol, fn):
        risky: list = []
        has_force = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            term, _recv = _call_desc(node)
            if term == "force":
                has_force = True
            if term != "record":
                continue
            sev = trace_kw = None
            for kw in node.keywords:
                if kw.arg == "severity":
                    sev = _severity_values(kw.value)
                elif kw.arg == "trace":
                    trace_kw = kw.value
            if sev and sev & RECORD_SEVERITIES and trace_kw is not None \
                    and not (
                isinstance(trace_kw, ast.Constant)
                and trace_kw.value is None
            ):
                risky.append(node)
        if has_force:
            return
        for node in risky:
            yield self.finding(
                RULE_FORCE,
                sf.path,
                node.lineno,
                "failure-path record() attaches a trace at severity "
                "warning/error but the function never .force()-samples "
                "the context — a head-sample miss leaves this incident "
                "without its trace",
                symbol=symbol,
            )

    # -- seam-wal-kind -----------------------------------------------------

    def _check_wal_kinds(self, index: ProjectIndex):
        kinds_sf = None
        for sf in index.files.values():
            if sf.path.endswith(self.kinds_module_suffix):
                kinds_sf = sf
                break
        if kinds_sf is None or kinds_sf.tree is None:
            return
        kind_defs: dict = {}     # name -> line
        names_map_keys: set = set()
        names_map_line = None
        for node in kinds_sf.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if not isinstance(t, ast.Name):
                    continue
                if t.id.startswith("KIND_") and t.id != "KIND_NAMES" and (
                    isinstance(node.value, ast.Constant)
                ):
                    kind_defs[t.id] = node.lineno
                elif t.id == "KIND_NAMES" and isinstance(
                    node.value, ast.Dict
                ):
                    names_map_line = node.lineno
                    for k in node.value.keys:
                        nm = dotted_name(k)
                        if nm:
                            names_map_keys.add(terminal_name(nm))
        if not kind_defs:
            return
        if names_map_line is not None:
            for name, line in sorted(kind_defs.items()):
                if name not in names_map_keys:
                    yield self.finding(
                        RULE_WAL_KIND,
                        kinds_sf.path,
                        line,
                        f"WAL record kind {name} is not mapped in "
                        "KIND_NAMES — encode_record() will reject it "
                        "and readers cannot label it",
                        symbol=name,
                    )
        for sf in index.files.values():
            if sf.tree is None or not any(
                sf.path.endswith(sfx) for sfx in self.handler_module_suffixes
            ):
                continue
            referenced = {
                node.id
                for node in ast.walk(sf.tree)
                if isinstance(node, ast.Name) and node.id.startswith("KIND_")
            }
            referenced |= {
                node.attr
                for node in ast.walk(sf.tree)
                if isinstance(node, ast.Attribute)
                and node.attr.startswith("KIND_")
            }
            for name, line in sorted(kind_defs.items()):
                if name not in referenced:
                    yield self.finding(
                        RULE_WAL_KIND,
                        sf.path,
                        1,
                        f"WAL record kind {name} "
                        f"({kinds_sf.path}:{line}) is never referenced "
                        "in this handler module — recovery would "
                        "silently skip or misfile records of this kind",
                        symbol=name,
                    )
