"""donation-aliasing: a buffer passed at a ``donate_argnums`` position
of a jitted kernel is DEAD after the call — XLA may reuse its memory for
the output in place.  Reading, returning, or caching it afterwards is
the exact bug class the PR 12 plan-cache ``_NativeEntry`` audit closed
by hand: the value observed is whatever the donated buffer was
overwritten with.

The checker resolves call sites against the project-wide jit registry
(decorated defs AND ``name = jax.jit(fn, donate_argnums=…)``
assignments), then runs a light intra-function dataflow walk: for every
name/attribute passed at a donated position, the FIRST subsequent event
on that name must be a (re)assignment.  The canonical safe idiom —
``dyn = step(statics, dyn, …)`` — rebinds the name in the same
statement and is recognized as such; ``*args`` splats are tracked
through the splatted name."""

from __future__ import annotations

import ast

from .base import (
    Checker,
    assign_targets,
    enclosing_statement,
    iter_functions,
    name_events,
)
from .project import ProjectIndex, call_func_name, dotted_name, terminal_name

RULE = "donation-aliasing"


class DonationChecker(Checker):
    name = "donation"
    rules = {RULE: "error"}

    def check(self, index: ProjectIndex):
        donating = index.donating()
        if not donating:
            return
        for sf in index.files.values():
            if sf.tree is None:
                continue
            for symbol, _cls, fn in iter_functions(sf):
                yield from self._check_function(
                    index, donating, sf, symbol, fn
                )

    def _check_function(self, index, donating, sf, symbol, fn):
        events = None  # built lazily, only when a donating call appears
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = terminal_name(call_func_name(node))
            info = donating.get(callee)
            if info is None or info.line == node.lineno and (
                info.path == sf.path
            ):
                # skip the def site itself (decorator line)
                continue
            tracked = self._donated_arg_names(node, info)
            if not tracked:
                continue
            stmt = enclosing_statement(fn, node)
            rebound = assign_targets(stmt) if stmt is not None else set()
            if events is None:
                events = name_events(fn)
            for argname in tracked:
                if argname in rebound:
                    continue
                hit = self._first_use_after(events, argname, node.lineno)
                if hit is not None:
                    yield self.finding(
                        RULE,
                        sf.path,
                        hit.line,
                        f"'{argname}' was donated to {callee}() at line "
                        f"{node.lineno} (donate_argnums, defined at "
                        f"{info.path}:{info.line}) and is read here "
                        "afterwards — the buffer may have been reused "
                        "in place; rebind it to the call's result or "
                        "copy before donating",
                        symbol=symbol,
                        col=hit.col,
                    )

    @staticmethod
    def _donated_arg_names(call: ast.Call, info) -> set:
        """Dotted names passed at the call's donated positions.  A
        ``*splat`` covering a donated position tracks the splatted name;
        inline tuples track each element."""
        names: set = set()
        star_at = None
        star_name = None
        for i, a in enumerate(call.args):
            if isinstance(a, ast.Starred):
                star_at = i
                star_name = dotted_name(a.value)
                break
        for pos in info.donate_argnums:
            expr = None
            if star_at is not None and pos >= star_at:
                if star_name:
                    names.add(star_name)
                continue
            if pos < len(call.args):
                expr = call.args[pos]
            elif info.params and pos < len(info.params):
                want = info.params[pos]
                for kw in call.keywords:
                    if kw.arg == want:
                        expr = kw.value
                        break
            if expr is None:
                continue
            if isinstance(expr, (ast.Tuple, ast.List)):
                for e in expr.elts:
                    d = dotted_name(e)
                    if d:
                        names.add(d)
            else:
                d = dotted_name(expr)
                if d:
                    names.add(d)
        return names

    @staticmethod
    def _first_use_after(events, name, call_line):
        """The first event on ``name`` (or an attribute of it) strictly
        after ``call_line``; returns it when it is a READ, else None."""
        dotprefix = name + "."
        for e in events:
            if e.line <= call_line:
                continue
            if e.name == name or e.name.startswith(dotprefix):
                return None if e.is_store else e
        return None
