"""knob/metric drift: the README is the operator contract — every
``YTPU_*`` environment knob the code reads and every ``ytpu_*`` metric
family it registers must appear there, and (for the curated knob
prefixes) nothing documented may be dead.

This subsumes the original ``scripts/check_metrics_schema.py`` README
cross-check with an AST collection pass (only *real* ``os.environ.get``
reads and literal ``.counter/.gauge/.histogram("ytpu_…")`` registrations
count — a knob named in a comment no longer satisfies the contract).
The old script survives as a thin shim over :func:`live_comparison`,
which additionally diffs the *live* registry (instantiating a provider
+ fleet) against the README — that import-time check needs jax and so
stays out of the pure-``ast`` lint path.

Rules:

- **knob-drift** — a ``YTPU_*`` env var read in code but absent from
  README (anchored at the read site), or documented under one of the
  curated :data:`KNOB_PREFIXES` yet read nowhere (anchored at its
  README line).
- **metric-drift** — a literal ``ytpu_*`` family registered in code but
  missing from README's Observability table (anchored at the
  registration), or a table row whose name appears nowhere in the
  source tree.
"""

from __future__ import annotations

import ast
import re

from .base import Checker
from .project import ProjectIndex, dotted_name

RULE_KNOB = "knob-drift"
RULE_METRIC = "metric-drift"

# the curated families whose documentation may not go stale; reads of
# ANY YTPU_* name must be documented, but only these prefixes are
# checked in the README -> code direction (test-only knobs like
# YTPU_FUZZ_ITERS are documented without being read by the package)
KNOB_PREFIXES = (
    "CHAOS", "RESILIENCE", "DLQ", "WAL", "PROF", "SLO", "NET", "FLEET",
    "TIER", "REPL", "FAILOVER", "PLAN", "ADM", "ADMIN", "TRACE",
    "BLACKBOX", "FLUSH", "LINT", "CLUSTER", "GATEWAY", "GEO", "TSDB",
    "COST",
)

KNOB_RE = re.compile(
    "YTPU_(?:" + "|".join(KNOB_PREFIXES) + r")_[A-Z0-9_]+"
)
_ANY_KNOB_RE = re.compile(r"YTPU_[A-Z0-9_]*[A-Z0-9]")
_NATIVE_GETENV_RE = re.compile(r"getenv\(\s*\"(YTPU_[A-Z0-9_]+)\"")
_METRIC_ROW_RE = re.compile(r"\|\s*`(ytpu_[a-z0-9_]+)`\s*\|")
_METRIC_METHODS = frozenset({"counter", "gauge", "histogram"})


_KNOB_LITERAL_RE = re.compile(r"YTPU_[A-Z0-9_]+\Z")


def _env_read_names(call: ast.Call):
    """YTPU_* names this call reads.  The package reads env through
    ``os.environ.get`` AND wrapper helpers (``_env_int(name, default)``,
    ``pick(value, name, default)``, ``_env_float(env, name)``) — so any
    ``"YTPU_X"`` string literal in argument position counts as a read."""
    for arg in list(call.args) + [kw.value for kw in call.keywords]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if _KNOB_LITERAL_RE.fullmatch(arg.value):
                yield arg.value


def _env_subscript_name(node: ast.Subscript):
    """``"YTPU_X"`` for ``os.environ["YTPU_X"]`` style access."""
    recv = dotted_name(node.value) or ""
    if not recv.endswith("environ"):
        return None
    sl = node.slice
    if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
        if _KNOB_LITERAL_RE.fullmatch(sl.value):
            return sl.value
    return None


def _metric_reg_name(call: ast.Call):
    """``"ytpu_x"`` when ``call`` is ``….counter/gauge/histogram("ytpu_x",
    …)``; else None."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _METRIC_METHODS:
        return None
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        if arg.value.startswith("ytpu_"):
            return arg.value
    return None


def documented_metrics(readme_text: str) -> set:
    """ytpu_* names from README's Observability table rows."""
    return {
        m.group(1)
        for line in readme_text.splitlines()
        for m in [_METRIC_ROW_RE.match(line)]
        if m
    }


def documented_knobs(readme_text: str) -> set:
    """Every YTPU_* name mentioned anywhere in the README."""
    return set(_ANY_KNOB_RE.findall(readme_text))


def knob_reads(index: ProjectIndex) -> dict:
    """name -> (path, line) of the first ``os.environ.get`` read."""
    out: dict = {}
    for sf in index.files.values():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                for nm in _env_read_names(node):
                    out.setdefault(nm, (sf.path, node.lineno))
            elif isinstance(node, ast.Subscript):
                nm = _env_subscript_name(node)
                if nm is not None:
                    out.setdefault(nm, (sf.path, node.lineno))
    return out


def metric_registrations(index: ProjectIndex) -> dict:
    """name -> (path, line) of the first literal registration."""
    out: dict = {}
    for sf in index.files.values():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                nm = _metric_reg_name(node)
                if nm is not None:
                    out.setdefault(nm, (sf.path, node.lineno))
    return out


def native_knob_reads(root, globs) -> dict:
    """``getenv("YTPU_X")`` reads in native (C/C++) sources — knobs the
    Python AST pass cannot see but which are real read sites."""
    from pathlib import Path

    out: dict = {}
    for pattern in globs:
        for p in sorted(Path(root).glob(pattern)):
            try:
                text = p.read_text(encoding="utf-8", errors="replace")
            except OSError:
                continue
            try:
                rel = p.resolve().relative_to(
                    Path(root).resolve()
                ).as_posix()
            except ValueError:
                rel = p.as_posix()
            for i, line in enumerate(text.splitlines(), start=1):
                for m in _NATIVE_GETENV_RE.finditer(line):
                    out.setdefault(m.group(1), (rel, i))
    return out


class DriftChecker(Checker):
    name = "drift"
    rules = {RULE_KNOB: "warning", RULE_METRIC: "warning"}

    NATIVE_GLOBS = (
        "yjs_tpu/native/*.c",
        "yjs_tpu/native/*.cc",
        "yjs_tpu/native/*.cpp",
        "yjs_tpu/native/*.h",
    )

    def __init__(
        self, readme_path: str = "README.md", stale_docs: bool = True
    ):
        self.readme_path = readme_path
        # the README -> code direction ("documented but dead") is only
        # meaningful when the WHOLE project is in the index — a partial
        # run (ytpu_lint some/file.py) would call every knob the target
        # doesn't happen to read stale.  The runner turns it off for
        # explicit-target runs.
        self.stale_docs = stale_docs

    def check(self, index: ProjectIndex):
        readme = index.read_adjacent(self.readme_path)
        if readme is None:
            return
        doc_knobs = documented_knobs(readme)
        doc_metrics = documented_metrics(readme)
        reads = knob_reads(index)
        for nm, loc in native_knob_reads(
            index.root, self.NATIVE_GLOBS
        ).items():
            reads.setdefault(nm, loc)
        regs = metric_registrations(index)

        for name in sorted(reads):
            if name not in doc_knobs:
                path, line = reads[name]
                yield self.finding(
                    RULE_KNOB,
                    path,
                    line,
                    f"env knob {name} is read here but never mentioned "
                    "in README — operators cannot discover it; add it "
                    "to the relevant knob table",
                    symbol=name,
                )
        # README -> code, curated prefixes only
        readme_lines = readme.splitlines()
        for name in sorted(doc_knobs) if self.stale_docs else ():
            if not KNOB_RE.fullmatch(name) or name in reads:
                continue
            if f"{name}_*" in readme or f"{name}*" in readme:
                continue  # wildcard family mention, not a single knob
            line = next(
                (
                    i + 1
                    for i, text in enumerate(readme_lines)
                    if name in text
                ),
                1,
            )
            yield self.finding(
                RULE_KNOB,
                self.readme_path,
                line,
                f"env knob {name} is documented in README but read "
                "nowhere in the package — stale docs; delete the row "
                "or wire the knob back up",
                symbol=name,
            )

        for name in sorted(regs):
            if name not in doc_metrics:
                path, line = regs[name]
                yield self.finding(
                    RULE_METRIC,
                    path,
                    line,
                    f"metric family {name} is registered here but has "
                    "no row in README's Observability table",
                    symbol=name,
                )
        all_text_names: set = set()
        for sf in index.files.values():
            all_text_names |= set(
                re.findall(r"ytpu_[a-z0-9_]+", sf.text)
            )
        for name in sorted(doc_metrics) if self.stale_docs else ():
            if name not in all_text_names:
                line = next(
                    (
                        i + 1
                        for i, text in enumerate(readme_lines)
                        if f"`{name}`" in text
                    ),
                    1,
                )
                yield self.finding(
                    RULE_METRIC,
                    self.readme_path,
                    line,
                    f"metric family {name} is documented in README's "
                    "Observability table but appears nowhere in the "
                    "source tree — stale row",
                    symbol=name,
                )


def live_comparison(root) -> list:
    """The original check_metrics_schema live diff: registered metric
    names (instantiating TpuProvider + FleetRouter) vs README's table,
    plus the curated-knob README/code cross-check.  Returns a list of
    human-readable problem strings (empty = in agreement).  Imports the
    package — callers needing a jax-free path use :class:`DriftChecker`.
    """
    from pathlib import Path

    root = Path(root)
    readme = (root / "README.md").read_text()
    problems: list = []

    from yjs_tpu.fleet import FleetRouter
    from yjs_tpu.obs import global_registry
    from yjs_tpu.provider import TpuProvider

    from .runner import register_lint_metric

    prov = TpuProvider(1)
    FleetRouter(1, 1)
    register_lint_metric()  # the lint counter is part of the contract
    # the cluster families are lazily-registered process-global
    # singletons (no Supervisor/Gateway is spun up here) — touch each
    # holder so the live set includes them
    from yjs_tpu.cluster.gateway import _GatewayMetricsSingleton
    from yjs_tpu.cluster.rpc import rpc_metrics
    from yjs_tpu.cluster.supervisor import _ClusterMetrics

    _GatewayMetricsSingleton.get()
    rpc_metrics()
    _ClusterMetrics()
    # ... as are the admin-plane and federation-scrape families
    # (ISSUE 16): first request / first scrape registers them
    from yjs_tpu.obs.admin import admin_metrics
    from yjs_tpu.obs.federate import fed_metrics

    admin_metrics()
    fed_metrics()
    # ... and the geo families (ISSUE 17): registered by the first
    # GeoReplicator; instantiating the metrics holder is enough
    from yjs_tpu.geo.replicator import GeoMetrics

    GeoMetrics()
    # ... and the TSDB store families (ISSUE 19): lazily registered by
    # the first sample/query — touch the holder (the ytpu_cost_*
    # families register on the provider registry at construction above)
    from yjs_tpu.obs.tsdb import tsdb_metrics

    tsdb_metrics()
    live = set(prov.engine.obs.registry.names()) | set(
        global_registry().names()
    )
    if not live:
        return []  # obs disabled (YTPU_OBS_DISABLED) — nothing to check
    doc = documented_metrics(readme)
    for n in sorted(live - doc):
        problems.append(
            f"registered but NOT in README's Observability table: {n}"
        )
    for n in sorted(doc - live):
        problems.append(f"documented in README but NOT registered: {n}")

    code_knobs: set = set()
    for path in (root / "yjs_tpu").rglob("*.py"):
        code_knobs |= set(KNOB_RE.findall(path.read_text()))
    doc_knobs = set(KNOB_RE.findall(readme))
    for n in sorted(code_knobs - doc_knobs):
        problems.append(f"env knob read by the code but NOT in README: {n}")
    for n in sorted(doc_knobs - code_knobs):
        problems.append(f"env knob in README but NOT read by the code: {n}")
    return problems
