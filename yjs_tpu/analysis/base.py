"""Checker base class + the function-scope walking helpers most
checkers share (enclosing-symbol naming, ordered name-event streams,
under-lock block tracking)."""

from __future__ import annotations

import ast
from dataclasses import dataclass

from .model import Finding
from .project import ProjectIndex, SourceFile, dotted_name


class Checker:
    """One lint rule family.  Subclasses set ``rules`` (id -> severity)
    and implement :meth:`check` yielding findings."""

    name = "checker"
    rules: dict = {}

    def check(self, index: ProjectIndex):  # pragma: no cover - interface
        raise NotImplementedError

    def finding(
        self, rule: str, path: str, line: int, message: str,
        symbol: str = "", col: int = 0,
    ) -> Finding:
        return Finding(
            rule=rule,
            severity=self.rules[rule],
            path=path,
            line=line,
            message=message,
            symbol=symbol,
            col=col,
        )


def iter_functions(sf: SourceFile):
    """Yield ``(symbol, class_name_or_None, fn_node)`` for every def in
    the file, nested defs included (symbol = "Class.method" / "fn" /
    "fn.<inner>")."""
    if sf.tree is None:
        return

    def visit(node, prefix, cls):
        for item in ast.iter_child_nodes(node):
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                sym = f"{prefix}{item.name}"
                yield sym, cls, item
                yield from visit(item, f"{sym}.", cls)
            elif isinstance(item, ast.ClassDef):
                yield from visit(item, f"{item.name}.", item.name)

    yield from visit(sf.tree, "", None)


@dataclass
class NameEvent:
    """One Load/Store/Del of a dotted name inside a function."""

    name: str
    line: int
    col: int
    is_store: bool


# method names that mutate their receiver in place — a call like
# ``self._ring.append(x)`` counts as a WRITE of ``self._ring``
MUTATING_METHODS = frozenset(
    {
        "append", "appendleft", "extend", "extendleft", "insert", "pop",
        "popleft", "popitem", "clear", "remove", "discard", "add",
        "update", "setdefault", "sort", "reverse", "move_to_end",
        "rotate",
    }
)


def name_events(fn, own_body_only: bool = True) -> list[NameEvent]:
    """Ordered Load/Store events of every dotted name in ``fn``.

    Subscript stores (``self._t[k] = v``) and mutating method calls
    (``self._ring.append(x)``) are reported as stores of the container
    name — that's the aliasing/locking granularity the checkers need.
    Nested function defs are skipped when ``own_body_only``."""
    events: list[NameEvent] = []
    skip: set = set()

    for node in ast.walk(fn):
        if own_body_only and isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ) and node is not fn:
            for sub in ast.walk(node):
                skip.add(id(sub))

    for node in ast.walk(fn):
        if id(node) in skip:
            continue
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if node.func.attr in MUTATING_METHODS:
                recv = dotted_name(node.func.value)
                if recv:
                    events.append(
                        NameEvent(recv, node.lineno, node.col_offset, True)
                    )
        elif isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            recv = dotted_name(node.value)
            if recv:
                events.append(
                    NameEvent(recv, node.lineno, node.col_offset, True)
                )
        elif isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted_name(node)
            if d is None:
                continue
            # only the OUTERMOST attribute chain: skip if this node is
            # the .value of a parent Attribute (handled via the parent)
            events.append(
                NameEvent(
                    d,
                    node.lineno,
                    node.col_offset,
                    isinstance(node.ctx, (ast.Store, ast.Del)),
                )
            )
    # de-dup inner chain fragments: "self" load inside "self._ring" —
    # keep the longest name at each (line, col)
    best: dict = {}
    for e in events:
        key = (e.line, e.col, e.is_store)
        cur = best.get(key)
        if cur is None or len(e.name) > len(cur.name):
            best[key] = e
    out = sorted(best.values(), key=lambda e: (e.line, e.col))
    return out


def assign_targets(stmt) -> set:
    """Dotted names a statement assigns (tuple targets flattened)."""
    out: set = set()

    def add(t):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                add(e)
        elif isinstance(t, ast.Starred):
            add(t.value)
        else:
            d = dotted_name(t)
            if d:
                out.add(d)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            add(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        add(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        add(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                add(item.optional_vars)
    return out


def enclosing_statement(fn, target) -> ast.stmt | None:
    """The direct statement inside ``fn`` (at any nesting depth) whose
    subtree contains ``target``."""
    result = None

    def visit(node):
        nonlocal result
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                if any(sub is target for sub in ast.walk(child)):
                    result = child
                    visit(child)
                    return
            else:
                visit(child)

    visit(fn)
    return result
