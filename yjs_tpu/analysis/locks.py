"""lock-discipline + lock-ordering: the race family PR 4 caught
dynamically (torn scrapes of ``FlushHistory`` / SLO deques mutating
under a concurrent flush) turned into a static contract.

- **lock-discipline** — an attribute that is ever *written* while
  holding a ``threading.Lock`` attribute of the same class is GUARDED:
  every other access (read or write) must also hold the lock.  The
  checker tracks ``with self._lock:`` blocks syntactically, counts
  in-place mutators (``.append``/``.pop``/subscript stores) as writes,
  and exempts ``__init__`` (pre-publication).  Module-level globals
  written under a module-level lock inside ``global``-declaring
  functions get the same treatment (the double-checked-locking fast
  path needs an explicit suppression with its justification).
- **lock-ordering** — syntactic lock nesting builds a directed graph
  (``with a: … with b:`` ⇒ a→b) over the whole project; any cycle is an
  ABBA deadlock waiting for the right interleaving and is reported on
  one of its edges.
"""

from __future__ import annotations

import ast

from .base import MUTATING_METHODS, Checker
from .project import ProjectIndex, SourceFile, dotted_name

RULE_DISCIPLINE = "lock-discipline"
RULE_ORDERING = "lock-ordering"

EXEMPT_METHODS = frozenset({"__init__", "__new__", "__post_init__"})


class _Access:
    __slots__ = ("attr", "line", "col", "is_write", "held", "method")

    def __init__(self, attr, line, col, is_write, held, method):
        self.attr = attr
        self.line = line
        self.col = col
        self.is_write = is_write
        self.held = held
        self.method = method


class LockChecker(Checker):
    name = "locks"
    rules = {RULE_DISCIPLINE: "warning", RULE_ORDERING: "error"}

    def check(self, index: ProjectIndex):
        self._edges: dict = {}  # (src, dst) -> (path, line)
        for sf in index.files.values():
            if sf.tree is None:
                continue
            for ci in sf.classes.values():
                if ci.lock_attrs:
                    yield from self._check_class(sf, ci)
            if sf.module_locks:
                yield from self._check_module(sf)
        yield from self._check_cycles()

    # -- class-attribute discipline ---------------------------------------

    def _check_class(self, sf: SourceFile, ci):
        accesses: list[_Access] = []
        for mname, fn in ci.methods.items():
            walker = _HeldWalker(
                owner="self.",
                lock_names={f"self.{a}" for a in ci.lock_attrs},
                lock_key=lambda nm, c=ci.name: f"{c}.{nm.split('.', 1)[1]}",
                edges=self._edges,
                path=sf.path,
            )
            walker.visit(fn, ())
            for attr, line, col, is_write, held in walker.accesses:
                if attr in ci.lock_attrs:
                    continue
                accesses.append(
                    _Access(attr, line, col, is_write, held, mname)
                )
        guarded = {
            a.attr for a in accesses if a.is_write and a.held
        }
        seen: set = set()
        for a in accesses:
            if a.attr not in guarded or a.held:
                continue
            if a.method in EXEMPT_METHODS:
                continue
            key = (a.attr, a.line)
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                RULE_DISCIPLINE,
                sf.path,
                a.line,
                f"'{a.attr}' is written under a lock elsewhere in "
                f"{ci.name} but {'written' if a.is_write else 'read'} "
                "lock-free here — take the lock or snapshot under it",
                symbol=f"{ci.name}.{a.method}",
                col=a.col,
            )

    # -- module-global discipline ------------------------------------------

    def _check_module(self, sf: SourceFile):
        accesses: list[_Access] = []
        modkey = sf.path.rsplit("/", 1)[-1]
        for fname, fn in sf.functions.items():
            declared_global = {
                n
                for node in ast.walk(fn)
                if isinstance(node, ast.Global)
                for n in node.names
            }
            local_names = _assigned_locals(fn) - declared_global
            walker = _HeldWalker(
                owner=None,
                lock_names=set(sf.module_locks),
                lock_key=lambda nm, m=modkey: f"{m}:{nm}",
                edges=self._edges,
                path=sf.path,
            )
            walker.visit(fn, ())
            for attr, line, col, is_write, held in walker.accesses:
                if attr in sf.module_locks or attr in local_names:
                    continue
                if is_write and attr not in declared_global:
                    continue
                accesses.append(
                    _Access(attr, line, col, is_write, held, fname)
                )
        guarded = {a.attr for a in accesses if a.is_write and a.held}
        seen: set = set()
        for a in accesses:
            if a.attr not in guarded or a.held:
                continue
            key = (a.attr, a.line)
            if key in seen:
                continue
            seen.add(key)
            yield self.finding(
                RULE_DISCIPLINE,
                sf.path,
                a.line,
                f"module global '{a.attr}' is written under a lock "
                f"elsewhere but {'written' if a.is_write else 'read'} "
                "lock-free here — take the lock or justify the "
                "double-checked fast path with a suppression",
                symbol=a.method,
                col=a.col,
            )

    # -- ordering cycles ---------------------------------------------------

    def _check_cycles(self):
        graph: dict = {}
        for (src, dst) in self._edges:
            graph.setdefault(src, set()).add(dst)
        reported: set = set()
        for start in sorted(graph):
            cycle = _find_cycle(graph, start)
            if not cycle:
                continue
            key = frozenset(cycle)
            if key in reported:
                continue
            reported.add(key)
            # anchor the finding on the edge closing the cycle
            src, dst = cycle[-1], cycle[0]
            path, line = self._edges.get(
                (src, dst), next(iter(self._edges.values()))
            )
            yield self.finding(
                RULE_ORDERING,
                path,
                line,
                "lock-ordering cycle: "
                + " -> ".join(cycle + [cycle[0]])
                + " — two threads taking these in opposite order "
                "deadlock; pick one global order",
                symbol="",
            )


class _HeldWalker:
    """Recursive AST walk tracking which locks are syntactically held,
    collecting attribute/global accesses with their held-set, and
    recording lock-nesting edges."""

    def __init__(self, owner, lock_names, lock_key, edges, path):
        self.owner = owner              # "self." for classes, None=globals
        self.lock_names = lock_names    # {"self._lock"} / {"_LOCK"}
        self.lock_key = lock_key
        self.edges = edges
        self.path = path
        self.accesses: list = []        # (attr, line, col, is_write, held)

    def visit(self, node, held):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in node.items:
                d = dotted_name(item.context_expr)
                if d in self.lock_names:
                    key = self.lock_key(d)
                    for prev in new_held:
                        self.edges.setdefault(
                            (prev, key), (self.path, node.lineno)
                        )
                    new_held = new_held + (key,)
                else:
                    self.visit(item.context_expr, held)
            for child in node.body:
                self.visit(child, new_held)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            getattr(self, "_entered", False)
        ):
            return  # nested defs escape the lock scope — skip
        self._entered = True
        if isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ) and node.func.attr in MUTATING_METHODS:
            recv = dotted_name(node.func.value)
            attr = self._attr_of(recv)
            if attr is not None:
                self.accesses.append(
                    (attr, node.lineno, node.col_offset, True, held)
                )
                for a in node.args:
                    self.visit(a, held)
                for kw in node.keywords:
                    self.visit(kw.value, held)
                return
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            recv = dotted_name(node.value)
            attr = self._attr_of(recv)
            if attr is not None:
                self.accesses.append(
                    (attr, node.lineno, node.col_offset, True, held)
                )
                self.visit(node.slice, held)
                return
        if isinstance(node, (ast.Attribute, ast.Name)):
            d = dotted_name(node)
            attr = self._attr_of(d)
            if attr is not None:
                self.accesses.append(
                    (
                        attr,
                        node.lineno,
                        node.col_offset,
                        isinstance(node.ctx, (ast.Store, ast.Del)),
                        held,
                    )
                )
                return  # don't descend into chain fragments
            if d is not None:
                return
        for child in ast.iter_child_nodes(node):
            self.visit(child, held)

    def _attr_of(self, dotted: str | None):
        if dotted is None:
            return None
        if self.owner is None:
            return dotted if "." not in dotted else None
        if dotted.startswith(self.owner):
            return dotted[len(self.owner):].split(".", 1)[0]
        return None


def _assigned_locals(fn) -> set:
    out: set = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            out.add(node.id)
    out |= {a.arg for a in fn.args.args}
    return out


def _find_cycle(graph, start):
    """A cycle reachable from ``start`` (list of nodes), or None."""
    stack: list = []
    on_stack: set = set()
    visited: set = set()

    def dfs(n):
        visited.add(n)
        stack.append(n)
        on_stack.add(n)
        for m in sorted(graph.get(n, ())):
            if m in on_stack:
                return stack[stack.index(m):]
            if m not in visited:
                got = dfs(m)
                if got:
                    return got
        stack.pop()
        on_stack.discard(n)
        return None

    return dfs(start)
