"""Shared finding/severity model for the ytpu-lint static-analysis suite.

One :class:`Finding` per rule violation, carrying the rule id, severity,
location, the enclosing symbol (``Class.method`` or module), and a
stable *fingerprint* — a keyed hash of (rule, path, symbol, message)
that deliberately excludes line numbers, so a committed baseline entry
survives unrelated edits to the same file.

Suppressions are inline comments, pylint-style but project-native::

    x = donated_call(buf)  # ytpu-lint: disable=donation-aliasing -- reason
    # ytpu-lint: disable-next-line=lock-discipline -- benign racy precheck
    # ytpu-lint: disable-file=retrace-hazard -- generated shim

A suppression MUST carry a ``-- reason`` string; a bare disable is
itself reported (rule ``bare-suppression``), and a disable that matched
no finding is reported as ``useless-suppression`` — so every committed
suppression is load-bearing and self-documenting, and deleting any one
of them reproduces the original finding.
"""

from __future__ import annotations

import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from hashlib import blake2b
from pathlib import Path

SEVERITIES = ("advice", "warning", "error")

# meta-rules emitted by the runner itself (not a checker)
RULE_USELESS_SUPPRESSION = "useless-suppression"
RULE_BARE_SUPPRESSION = "bare-suppression"
RULE_PARSE_ERROR = "parse-error"

_SUPPRESS_RE = re.compile(
    r"#\s*ytpu-lint:\s*"
    r"(?P<kind>disable|disable-next-line|disable-file)\s*=\s*"
    r"(?P<rules>[a-z0-9_,\- ]+?)"
    r"(?:\s*--\s*(?P<reason>.*\S))?\s*$"
)


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    severity: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str
    symbol: str = ""   # enclosing "Class.method" / "function" / ""
    col: int = 0

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for baseline matching."""
        h = blake2b(digest_size=8, person=b"ytpu-lint")
        for part in (self.rule, self.path, self.symbol, self.message):
            h.update(part.encode("utf-8", "replace"))
            h.update(b"\x00")
        return h.hexdigest()

    def render(self) -> str:
        loc = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{loc}: {self.severity}: {self.rule}: {self.message}{sym}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "symbol": self.symbol,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


@dataclass
class Suppression:
    """One parsed ``# ytpu-lint: disable…`` comment."""

    path: str
    line: int            # line the comment sits on
    kind: str            # disable | disable-next-line | disable-file
    rules: tuple
    reason: str
    used: bool = field(default=False, compare=False)

    @property
    def target_line(self) -> int | None:
        """The source line this suppression covers (None = whole file)."""
        if self.kind == "disable":
            return self.line
        if self.kind == "disable-next-line":
            return self.line + 1
        return None

    def covers(self, finding: Finding) -> bool:
        if finding.path != self.path:
            return False
        if finding.rule not in self.rules and "all" not in self.rules:
            return False
        target = self.target_line
        return target is None or target == finding.line


def parse_suppressions(path: str, text: str) -> list[Suppression]:
    """Suppressions from real COMMENT tokens only — a ``# ytpu-lint:``
    example quoted inside a docstring is documentation, not a disable."""
    if "ytpu-lint" not in text:
        return []
    out = []
    try:
        tokens = list(
            tokenize.generate_tokens(io.StringIO(text).readline)
        )
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return []
    for tok in tokens:
        if tok.type != tokenize.COMMENT or "ytpu-lint" not in tok.string:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = tuple(
            r.strip() for r in m.group("rules").split(",") if r.strip()
        )
        out.append(
            Suppression(
                path=path,
                line=tok.start[0],
                kind=m.group("kind"),
                rules=rules,
                reason=(m.group("reason") or "").strip(),
            )
        )
    return out


class Baseline:
    """Committed fingerprints of grandfathered findings.

    The file is a JSON list of entries ``{"fingerprint", "rule", "path",
    "symbol", "message", "note"}``; everything except the fingerprint is
    for the human reading the diff.  An entry that matches no live
    finding is *stale* and reported, so the baseline can only shrink."""

    def __init__(self, entries: list[dict] | None = None):
        self.entries = list(entries or [])
        self._by_fp = {e["fingerprint"]: e for e in self.entries}
        self.matched: set[str] = set()

    @classmethod
    def load(cls, path: str | Path) -> "Baseline":
        p = Path(path)
        if not p.exists():
            return cls([])
        return cls(json.loads(p.read_text()))

    def save(self, path: str | Path) -> None:
        Path(path).write_text(
            json.dumps(self.entries, indent=1, sort_keys=True) + "\n"
        )

    def covers(self, finding: Finding) -> bool:
        fp = finding.fingerprint
        if fp in self._by_fp:
            self.matched.add(fp)
            return True
        return False

    def stale_entries(self) -> list[dict]:
        return [
            e for e in self.entries if e["fingerprint"] not in self.matched
        ]

    @staticmethod
    def entry_for(finding: Finding, note: str = "") -> dict:
        return {
            "fingerprint": finding.fingerprint,
            "rule": finding.rule,
            "path": finding.path,
            "symbol": finding.symbol,
            "message": finding.message,
            "note": note,
        }
