"""Lint run orchestration: build the :class:`ProjectIndex`, run every
checker, apply inline suppressions and the committed baseline, and emit
the meta-findings that keep the escape hatches honest:

- ``bare-suppression`` — a ``disable=`` comment without a ``-- reason``;
- ``useless-suppression`` — a suppression that matched nothing (so
  deleting any real suppression reproduces its finding, and a fixed
  finding forces its suppression to be removed);
- stale baseline entries — a baseline fingerprint that matched nothing
  (the baseline can only shrink).

The run also feeds the observability registry when one is importable:
``ytpu_lint_findings_total{rule,severity}`` counts every raw finding
(pre-suppression), so a fleet dashboard can watch debt trend toward
zero without parsing lint output.  The import is best-effort — the lint
path itself never needs jax.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from .base import Checker
from .donation import DonationChecker
from .drift import DriftChecker
from .locks import LockChecker
from .model import (
    Baseline,
    Finding,
    RULE_BARE_SUPPRESSION,
    RULE_USELESS_SUPPRESSION,
    parse_suppressions,
)
from .project import ProjectIndex, iter_python_files
from .retrace import RetraceChecker
from .seams import SeamChecker

DEFAULT_EXCLUDE = ("tests", ".git", "__pycache__", "build", "dist")


def default_checkers(stale_docs: bool = True) -> list[Checker]:
    return [
        DonationChecker(),
        RetraceChecker(),
        LockChecker(),
        SeamChecker(),
        DriftChecker(stale_docs=stale_docs),
    ]


def all_rules(checkers=None) -> dict:
    """rule id -> severity for every registered rule + the meta rules."""
    out = {
        RULE_BARE_SUPPRESSION: "warning",
        RULE_USELESS_SUPPRESSION: "warning",
        "parse-error": "error",
    }
    for c in checkers or default_checkers():
        out.update(c.rules)
    return out


@dataclass
class LintResult:
    """Everything one run produced, pre-partitioned for reporting."""

    findings: list = field(default_factory=list)   # active (reportable)
    suppressed: list = field(default_factory=list)
    baselined: list = field(default_factory=list)
    stale_baseline: list = field(default_factory=list)
    raw_count: int = 0

    @property
    def failed(self) -> bool:
        return bool(self.findings or self.stale_baseline)

    def counts(self) -> dict:
        out: dict = {}
        for f in self.findings:
            key = (f.rule, f.severity)
            out[key] = out.get(key, 0) + 1
        return out


def run_lint(
    root,
    targets=None,
    checkers=None,
    baseline: Baseline | None = None,
    exclude: tuple = DEFAULT_EXCLUDE,
    emit_metrics: bool = True,
) -> LintResult:
    root = Path(root)
    full_run = targets is None
    if targets is None:
        targets = [root / "yjs_tpu", root / "scripts"]
        if (root / "bench.py").is_file():
            targets.append(root / "bench.py")
    paths = iter_python_files([Path(t) for t in targets], exclude=exclude)
    index = ProjectIndex(root, paths)
    # explicit targets = a partial view of the project: the drift
    # checker's "documented but dead" direction would flag every knob
    # the targeted files don't happen to read, so it runs only on full
    # sweeps (pass checkers=default_checkers() to override)
    checkers = (
        list(checkers)
        if checkers is not None
        else default_checkers(stale_docs=full_run)
    )
    baseline = baseline or Baseline([])

    raw: list[Finding] = list(index.parse_findings)
    for checker in checkers:
        raw.extend(checker.check(index))

    suppressions = []
    for sf in index.files.values():
        suppressions.extend(parse_suppressions(sf.path, sf.text))

    result = LintResult(raw_count=len(raw))
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        sup = next((s for s in suppressions if s.covers(f)), None)
        if sup is not None:
            sup.used = True
            result.suppressed.append(f)
            continue
        if baseline.covers(f):
            result.baselined.append(f)
            continue
        result.findings.append(f)

    for s in suppressions:
        if not s.reason:
            result.findings.append(
                Finding(
                    rule=RULE_BARE_SUPPRESSION,
                    severity="warning",
                    path=s.path,
                    line=s.line,
                    message=(
                        "suppression without a '-- reason' — every "
                        "disable must say why it is safe"
                    ),
                    symbol=",".join(s.rules),
                )
            )
        if not s.used:
            result.findings.append(
                Finding(
                    rule=RULE_USELESS_SUPPRESSION,
                    severity="warning",
                    path=s.path,
                    line=s.line,
                    message=(
                        f"suppression of {','.join(s.rules)} matched no "
                        "finding — the hazard is gone; delete the comment"
                    ),
                    symbol=",".join(s.rules),
                )
            )
    result.stale_baseline = baseline.stale_entries()
    result.findings.sort(key=lambda f: (f.path, f.line, f.rule))

    if emit_metrics:
        _emit_metrics(raw)
    return result


def register_lint_metric():
    """The process-global findings counter (idempotent — the registry
    returns the existing family on re-registration)."""
    from yjs_tpu.obs import global_registry

    return global_registry().counter(
        "ytpu_lint_findings_total",
        "static-analysis findings per run, pre-suppression",
        unit="findings",
        labelnames=("rule", "severity"),
    )


def _emit_metrics(raw_findings) -> None:
    """Count raw findings on the process-global registry, best-effort
    (the registry import pulls in numpy-free obs core only; any failure
    leaves the lint result untouched)."""
    try:
        counter = register_lint_metric()
        for f in raw_findings:
            counter.labels(rule=f.rule, severity=f.severity).inc()
    except Exception:
        pass


def render_report(result: LintResult, verbose: bool = False) -> str:
    lines: list = []
    for f in result.findings:
        lines.append(f.render())
    for e in result.stale_baseline:
        lines.append(
            f"{e['path']}: error: stale-baseline: baseline entry "
            f"{e['fingerprint']} ({e['rule']}: {e['message'][:60]}…) "
            "matched no finding — remove it from the baseline file"
        )
    if verbose and result.suppressed:
        lines.append("")
        for f in result.suppressed:
            lines.append(f"suppressed: {f.render()}")
    if verbose and result.baselined:
        lines.append("")
        for f in result.baselined:
            lines.append(f"baselined:  {f.render()}")
    n_err = sum(1 for f in result.findings if f.severity == "error")
    n_warn = sum(1 for f in result.findings if f.severity == "warning")
    lines.append(
        f"ytpu-lint: {len(result.findings)} finding(s) "
        f"({n_err} error, {n_warn} warning), "
        f"{len(result.suppressed)} suppressed, "
        f"{len(result.baselined)} baselined, "
        f"{len(result.stale_baseline)} stale baseline entr"
        f"{'y' if len(result.stale_baseline) == 1 else 'ies'}"
    )
    return "\n".join(lines)
