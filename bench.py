"""Benchmark: batched device applyUpdate vs the single-threaded CPU core.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Three variants, all reported in "detail" (VERDICT r1 item 2: end-to-end
timing including host transcode, distinct-vs-broadcast, B4 scale):

1. **b4_broadcast** (the headline): every doc replays the same B4-scale
   editing trace (tests/fixtures/b4_trace.bin — 182k single-char inserts /
   77k deletes with the real B4's sequential-typing texture, synthesized by
   scripts/gen_b4_fixture.py because the real crdt-benchmarks dataset is
   not retrievable here; statistics per reference INTERNALS.md:128-130).
   This is BASELINE.json's "100k-doc Y.Text B4-trace replay" shape: the
   trace is transcoded ONCE on the host and the plan broadcast across the
   batch.  End-to-end time INCLUDES host transcode + padding/pack + the
   host->device transfer + device integration + a readback barrier.
2. **distinct**: every doc receives a *different* trace through the full
   product path (BatchEngine.flush: per-doc decode, causal schedule,
   pre-split, pack, dispatch).  No broadcast amortization — this is the
   honest per-doc host cost, and it is host-bound (see detail timers).
3. **sync**: batched sync-step-2 (encodeStateAsUpdate against a remote
   state vector) across all distinct docs in one diff_mask_kernel dispatch.

Baseline: the repo's own single-threaded CPU reference core measures
`cpu_py_*` on the same traces.  Node.js is NOT available in this image, so
the north-star "single-threaded Node applyUpdate rate" is estimated as
cpu_py_rate x NODE_PROXY_FACTOR (default 20; see BASELINE.md "Node proxy"
for the calibration argument and sensitivity).  vs_baseline is measured
against that PROXY, not against Python.

Env knobs: YTPU_BENCH_DOCS (b4 broadcast batch, default 16384),
YTPU_BENCH_DISTINCT_DOCS (default 1024 when the pre-generated fixture
tests/fixtures/distinct_traces_*.bin exists — scripts/
gen_distinct_fixtures.py — else 64), YTPU_BENCH_OPS (distinct trace ops,
default 1500), YTPU_NODE_PROXY_FACTOR (default 20).
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import sys
import time
from pathlib import Path

import numpy as np

NODE_PROXY_FACTOR = float(os.environ.get("YTPU_NODE_PROXY_FACTOR", "20"))


def gen_trace(n_ops: int, seed: int = 7, n_clients: int = 2,
              sync_p: float = 0.3):
    """Concurrent editing trace: ``n_clients`` clients, typing bursts +
    deletes + periodic full syncs (probability ``sync_p`` per burst).
    The default (2 clients, 0.3) is the classic distinct-doc texture; the
    conflict-storm shape uses 4 clients with rare syncs, so long
    concurrent runs collide at the same positions (deep YATA conflict
    scans, heavy pre-splitting).  Returns (merged update, reference doc)."""
    import yjs_tpu as Y

    gen = random.Random(seed)
    docs = []
    for k in range(n_clients):
        d = Y.Doc(gc=False)
        d.client_id = 101 * (k + 1)
        docs.append(d)
    words = ["the ", "quick ", "brown ", "fox ", "jumps ", "over ", "lazy ", "dog . "]

    def sync():
        for da in docs:
            for db in docs:
                if da is db:
                    continue
                u = Y.encode_state_as_update(da, Y.encode_state_vector(db))
                Y.apply_update(db, u)

    ops = 0
    while ops < n_ops:
        # one gen.random() draw (for n_clients=2 this reproduces the r2-r4
        # fixture generator's RNG stream exactly: int(r*2)==0 <=> r<0.5)
        d = docs[min(n_clients - 1, int(gen.random() * n_clients))]
        t = d.get_text("text")
        cursor = gen.randint(0, len(t))
        burst = gen.randint(3, 12)
        for _ in range(burst):  # typing burst at a cursor
            if gen.random() < 0.8 or len(t) == 0:
                w = gen.choice(words)
                cursor = min(cursor, len(t))
                t.insert(cursor, w)
                cursor += len(w)
            else:
                pos = gen.randrange(len(t))
                n = min(gen.randint(1, 4), len(t) - pos)
                t.delete(pos, n)
                cursor = min(cursor, len(t))
            ops += 1
        if gen.random() < sync_p:
            sync()
    sync()
    ref = docs[0].get_text("text").to_string()
    for d in docs[1:]:
        assert d.get_text("text").to_string() == ref
    return Y.encode_state_as_update(docs[0]), docs[0]


def gen_prepend_fragmented(n_chars: int, seed: int = 3):
    """The reference's own worst-case perf probe (y-text.tests.js:297-324):
    N single-char inserts all at position 0.  No two items can ever merge
    (each prepended item has a null origin), so the doc is one item per
    character — maximal struct count per content byte."""
    import yjs_tpu as Y

    gen = random.Random(seed)
    d = Y.Doc(gc=False)
    d.client_id = 77
    t = d.get_text("text")
    for _ in range(n_chars):
        t.insert(0, chr(gen.randint(97, 122)))
    return Y.encode_state_as_update(d), d


def cpu_apply_rate(update: bytes, repeats: int = 1) -> tuple[float, int]:
    """Single-threaded CPU reference-core applyUpdate rate on one update
    (median of ``repeats`` runs — interpreter variance is real).  Returns
    (elements/sec, n_elements) where elements = integrated clocks."""
    import yjs_tpu as Y

    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        doc = Y.Doc(gc=False)
        Y.apply_update(doc, update)
        times.append(time.perf_counter() - t0)
    dt = sorted(times)[len(times) // 2]
    sv = Y.decode_state_vector(Y.encode_state_vector(doc))
    n_elements = sum(sv.values())
    return (n_elements / dt if dt > 0 else 0.0), n_elements


# ---------------------------------------------------------------------------
# Variant 1: B4-scale broadcast replay (transcode once, integrate B docs)
# ---------------------------------------------------------------------------


def bench_b4_broadcast(n_docs: int) -> dict:
    import jax.numpy as jnp

    from yjs_tpu.ops import kernels
    from yjs_tpu.ops.columns import NULL, DocMirror
    from yjs_tpu.ops.engine import visible_text

    fixtures = Path(__file__).resolve().parent / "tests" / "fixtures"
    b4_path = fixtures / "b4_trace.bin"
    if b4_path.exists():
        update = b4_path.read_bytes()
        meta = json.loads((fixtures / "b4_trace.json").read_text())
        trace_name = "b4_fixture"
    else:  # standalone fallback: synthesize a smaller trace on the fly
        update, ref_doc = gen_trace(int(os.environ.get("YTPU_BENCH_OPS", "1500")))
        text = ref_doc.get_text("text").to_string()
        meta = {
            "text_len": len(text),
            "text_sha256": hashlib.sha256(text.encode()).hexdigest(),
        }
        trace_name = "synthetic_small (b4 fixture missing)"

    cpu_rate, n_elements = cpu_apply_rate(update, repeats=3)

    # ---- host transcode (ONCE — the broadcast amortization) --------------
    t0 = time.perf_counter()
    try:
        from yjs_tpu.ops.native_mirror import NativeMirror, native_plan_available

        mirror = NativeMirror("text") if native_plan_available() else DocMirror("text")
    except Exception:
        mirror = DocMirror("text")
    mirror.ingest(update, v2=False)
    plan = mirror.prepare_step()
    t_transcode = time.perf_counter() - t0

    # ---- pack + pad + host->device transfer ------------------------------
    # the planner resolved every link host-side (plan.link_*): the batch
    # integration is ONE broadcast scatter of final links + heads + deletes
    # (kernels.apply_plan_shared) — the minimal B x state write.
    np.asarray(jnp.zeros(4, jnp.int32))  # device/tunnel first-contact warm
    t0 = time.perf_counter()
    n = mirror.n_rows
    cap = max(64, n)
    seg_cap = max(8, mirror.n_segs)

    def pad_lanes(idx, vals, bucket_min, oob):
        k = len(idx)
        padded = max(bucket_min, 1 << max(0, (k - 1).bit_length()))
        i = np.full(padded, oob, np.int32)
        i[:k] = np.asarray(idx, np.int32)
        if vals is None:
            return i
        v = np.full(padded, NULL, np.int32)
        v[:k] = np.asarray(vals, np.int32)
        return i, v

    rows_p, vals_p = pad_lanes(plan.link_rows, plan.link_vals, 64, cap + 1)
    segs_p, hvals_p = pad_lanes(plan.head_segs, plan.head_vals, 8, seg_cap + 1)
    dels_p = pad_lanes(plan.delete_rows, None, 64, cap + 1)
    k_l, k_h, k_d = len(rows_p), len(segs_p), len(dels_p)
    lanes_d = jnp.asarray(
        np.concatenate([rows_p, vals_p, segs_p, hvals_p, dels_p])
    )

    def fresh_dyn():
        return (
            jnp.full((n_docs, cap + 1), NULL, jnp.int32),
            jnp.zeros((n_docs, cap + 1), bool),
            jnp.full((n_docs, seg_cap + 1), NULL, jnp.int32),
        )

    # readback barrier (block_until_ready does not synchronize on the axon
    # tunnel backend): the transfer may not escape the timed window.
    # jax.device_get avoids compiling a slice program inside the timed
    # region (a first-compile on the tunnel costs ~0.8s)
    import jax

    jax.device_get(lanes_d)
    t_pack = time.perf_counter() - t0

    step = lambda dyn: kernels.apply_plan_shared(dyn, lanes_d, k_l, k_h, k_d)

    # warmup/compile excluded (cached for all later runs; block via readback
    # because block_until_ready does not synchronize on the axon tunnel)
    out = step(fresh_dyn())
    np.asarray(out[2])

    # device-only: K chained dispatches, one readback barrier
    K = 4
    t0 = time.perf_counter()
    for _ in range(K):
        out = step(fresh_dyn())
    np.asarray(out[0][:, 0])
    t_device = (time.perf_counter() - t0) / K

    # ---- convergence check: doc 0's visible text vs the reference --------
    right, deleted, start = out
    text_seg = mirror.segments[("text", None, NULL)]
    valid = np.zeros(cap + 1, bool)
    valid[:n] = np.asarray(mirror.row_seg, np.int32) == text_seg
    d = np.asarray(kernels.list_ranks(right[:1], jnp.asarray(valid)[None]))[0]
    dels_out = np.asarray(deleted[0])
    rows = np.nonzero(d >= 0)[0]
    rows = rows[np.argsort(-d[rows], kind="stable")]
    text = visible_text(mirror, rows, dels_out[rows])
    if (
        len(text) != meta["text_len"]
        or hashlib.sha256(text.encode()).hexdigest() != meta["text_sha256"]
    ):
        print(json.dumps({"metric": "FAILED_b4_convergence", "value": 0,
                          "unit": "", "vs_baseline": 0}))
        sys.exit(1)

    t_e2e = t_transcode + t_pack + t_device
    total_elems = n_docs * n_elements
    return {
        "trace": trace_name,
        "n_docs": n_docs,
        "elems_per_doc": n_elements,
        "n_rows": n,
        "n_link_lanes": len(plan.link_rows),
        "t_transcode_s": round(t_transcode, 4),
        "t_pack_s": round(t_pack, 4),
        "t_device_s": round(t_device, 4),
        "e2e_elems_per_sec": round(total_elems / t_e2e, 1),
        "device_elems_per_sec": round(total_elems / t_device, 1),
        "cpu_py_elems_per_sec": round(cpu_rate, 1),
    }


# ---------------------------------------------------------------------------
# Variant 2: distinct traffic through the full product path (BatchEngine)
# ---------------------------------------------------------------------------


def load_distinct_traces(
    n_docs: int, n_ops: int, kind: str = "distinct"
) -> list[bytes]:
    """Pre-generated traces (scripts/gen_distinct_fixtures.py; ``kind`` =
    "distinct" two-client or "storm" four-client); falls back to
    in-process synthesis when the fixture is missing.

    When ``n_docs`` exceeds the fixture, traces repeat cyclically: every
    doc still gets its own mirror/plan/transfer (per-doc host cost is
    trace-content-independent), so scaling sweeps measure the framework,
    not the fixture generator."""
    import struct
    import zlib

    stem = "distinct_traces" if kind == "distinct" else "storm_traces"
    path = (
        Path(__file__).resolve().parent
        / "tests" / "fixtures" / f"{stem}_{n_ops}.bin"
    )
    zpath = path.with_suffix(".bin.z")
    if path.exists() or zpath.exists():
        raw = (
            zlib.decompress(zpath.read_bytes())
            if zpath.exists()
            else path.read_bytes()
        )
        n, ops = struct.unpack_from("<II", raw, 0)
        assert ops == n_ops
        out, o = [], 8
        for _ in range(min(n, n_docs)):
            (ln,) = struct.unpack_from("<I", raw, o)
            out.append(raw[o + 4 : o + 4 + ln])
            o += 4 + ln
        if out:
            return [out[i % len(out)] for i in range(n_docs)]
    n_clients, sync_p = (2, 0.3) if kind == "distinct" else (4, 0.08)
    base = [
        gen_trace(n_ops, seed=1000 + i, n_clients=n_clients, sync_p=sync_p)[0]
        for i in range(min(n_docs, 64))
    ]
    return [base[i % len(base)] for i in range(n_docs)]


def bench_distinct(
    n_docs: int, n_ops: int, kind: str = "distinct", runs: int = 3
) -> tuple[dict, object]:
    from yjs_tpu.ops import BatchEngine

    # workload acquisition (per-doc distinct traces) — NOT timed: this
    # stands in for network receive, not for framework work
    updates = load_distinct_traces(n_docs, n_ops, kind=kind)
    # CPU oracle rate per UNIQUE trace (cyclic fixtures repeat bytes; the
    # engine cost per doc is identical either way)
    cpu_elems, cpu_time = 0, 0.0
    unique: dict[bytes, tuple[float, int]] = {}
    for u in updates:
        if u not in unique:
            rate, n_el = cpu_apply_rate(u)
            unique[u] = (n_el / rate if rate else 0.0, n_el)
        t_u, n_el = unique[u]
        cpu_elems += n_el
        cpu_time += t_u
    n_unique = len(unique)
    del unique

    # compile warmup: an identically-shaped engine run (fresh engine, same
    # updates -> same padded bucket shapes -> compile cache hit in the timed
    # run).  Steady-state server behavior; compile time excluded, as stated.
    eng = BatchEngine(n_docs)
    for i, u in enumerate(updates):
        eng.queue_update(i, u)
    eng.flush()
    np.asarray(eng._right[:, 0])

    # the oracle pass above built ~1k full CPU docs (millions of heap
    # objects a real server would not hold); freeze them out of the GC so
    # gen2 collections don't bill the timed loop for the test harness.
    # The warmup engine must die BEFORE the freeze: frozen objects are
    # invisible to the cycle collector, and a frozen engine's mirrors
    # (self._py cycle) would leak their C++ state through every run.
    import gc

    eng = None
    gc.collect()
    gc.freeze()

    # median of ``runs`` timed runs: host-core and tunnel contention swing
    # single runs 2-4x (BASELINE.md), and the server shape is steady-state.
    # ONE engine alive at a time (a server holds one engine; stacking
    # 200MB+ mirror states from prior runs thrashes the single host core)
    timed = []  # (dt, flush metrics) pairs; sorted by dt for the median
    for _ in range(runs):
        # free the previous engine and let the device-side buffer deletes
        # drain BEFORE the timed window (cleanup RPCs otherwise steal the
        # single host core mid-run and inflate plan timers 2-3x)
        eng = None
        gc.collect()
        time.sleep(3)
        eng = BatchEngine(n_docs)
        t0 = time.perf_counter()
        for i, u in enumerate(updates):
            eng.queue_update(i, u)
        eng.flush()
        # readback barrier: force device completion
        np.asarray(eng._right[:, 0])
        dt = time.perf_counter() - t0
        timed.append((dt, eng.last_flush_metrics))
    gc.unfreeze()
    timed.sort(key=lambda p: p[0])
    t_e2e, eng_metrics = timed[len(timed) // 2]  # median run (its metrics)

    # convergence spot-check on 3 docs (distinct traces -> meaningful)
    import yjs_tpu as Y

    for i in random.Random(3).sample(range(n_docs), min(3, n_docs)):
        d = Y.Doc(gc=False)
        Y.apply_update(d, updates[i])
        if eng.text(i) != d.get_text("text").to_string():
            print(json.dumps({"metric": "FAILED_distinct_convergence",
                              "value": 0, "unit": "", "vs_baseline": 0}))
            sys.exit(1)

    m = eng_metrics or {}
    return (
        {
            "n_docs": n_docs,
            "trace_ops": n_ops,
            "total_elems": cpu_elems,
            "e2e_elems_per_sec": round(cpu_elems / t_e2e, 1),
            "cpu_py_elems_per_sec": round(cpu_elems / cpu_time, 1) if cpu_time else 0,
            "t_e2e_s": round(t_e2e, 4),
            "host_phase_timers_s": {
                k: round(m.get(k, 0.0), 4)
                for k in ("t_plan_s", "t_pack_s", "t_dispatch_s")
            },
            # host transcode (decode + causal schedule + pre-split) per doc
            "transcode_ms_per_doc": round(
                m.get("t_plan_s", 0.0) / max(1, n_docs) * 1e3, 3
            ),
            "schedule_occupancy": round(m.get("schedule_occupancy", 0.0), 4),
            "plan_threads": m.get("plan_threads", 1),
            "n_demoted": m.get("n_demoted", 0),
            # honesty marker: docs repeat trace BYTES cyclically when the
            # fixture (or synthesis fallback) holds fewer unique traces
            # than docs — per-doc engine work is identical either way,
            # but the reader must see the repetition (no silent caps)
            "unique_traces": n_unique,
        },
        eng,
    )


# ---------------------------------------------------------------------------
# Adversarial shapes (VERDICT r4 item 8)
# ---------------------------------------------------------------------------


def bench_fragmented(n_docs: int, n_chars: int) -> dict:
    """The reference's worst-case perf probe at batch scale: every doc is
    a maximally fragmented prepend-built text (one item per character,
    y-text.tests.js:297-324), replicated across ``n_docs`` mirrors.
    Reports planner ms/doc and occupancy under the nastiest struct-per-
    byte ratio the reference itself measures."""
    import gc

    from yjs_tpu.ops import BatchEngine

    update = load_prepend_fixture(n_chars)
    cpu_rate, n_el = cpu_apply_rate(update)
    eng = BatchEngine(n_docs)
    for i in range(n_docs):
        eng.queue_update(i, update)
    eng.flush()  # warmup/compile
    np.asarray(eng._right[:, 0])
    expect = None
    import yjs_tpu as Y

    d = Y.Doc(gc=False)
    Y.apply_update(d, update)
    expect = d.get_text("text").to_string()
    if eng.text(0) != expect:
        print(json.dumps({"metric": "FAILED_fragmented_convergence",
                          "value": 0, "unit": "", "vs_baseline": 0}))
        sys.exit(1)
    eng = None
    gc.collect()
    time.sleep(3)
    eng = BatchEngine(n_docs)
    t0 = time.perf_counter()
    for i in range(n_docs):
        eng.queue_update(i, update)
    eng.flush()
    np.asarray(eng._right[:, 0])
    dt = time.perf_counter() - t0
    m = eng.last_flush_metrics or {}
    total = n_docs * n_el
    res = {
        "n_docs": n_docs,
        "chars_per_doc": n_chars,
        "update_bytes": len(update),
        "e2e_elems_per_sec": round(total / dt, 1),
        "cpu_py_elems_per_sec": round(cpu_rate, 1),
        "t_e2e_s": round(dt, 4),
        "planner_ms_per_doc": round(
            m.get("t_plan_s", 0.0) / max(1, n_docs) * 1e3, 3
        ),
        # per-phase host wall time straight off the shared
        # new_flush_metrics() schema (the same keys every flush reports)
        "host_phase_timers_s": {
            k: round(m.get(k, 0.0), 5)
            for k in (
                "t_compact_s", "t_plan_s", "t_plan_cached_s",
                "t_plan_cold_s", "t_pack_s", "t_dispatch_s", "t_emit_s",
                "t_total_s",
            )
        },
        "plan_threads": m.get("plan_threads", 1),
        "plan_cache_hits": m.get("plan_cache_hits", 0),
        "plan_cache_misses": m.get("plan_cache_misses", 0),
        "schedule_occupancy": round(m.get("schedule_occupancy", 0.0), 4),
        "n_demoted": m.get("n_demoted", 0),
    }
    del eng
    gc.collect()
    return res


def load_prepend_fixture(n_chars: int) -> bytes:
    """Pre-generated prepend-fragmented update
    (scripts/gen_adversarial_fixtures.py); synthesized at a smaller size
    when the fixture is missing (generation is O(n) CPU-core edits)."""
    import zlib

    path = (
        Path(__file__).resolve().parent
        / "tests" / "fixtures" / f"prepend_frag_{n_chars}.bin.z"
    )
    if path.exists():
        return zlib.decompress(path.read_bytes())
    return gen_prepend_fragmented(n_chars)[0]


def bench_planner(
    n_docs: int = 32, n_chars: int = 20000, reps: int = 5
) -> dict:
    """detail.planner → BENCH_planner.json: plan-cache effectiveness
    (ISSUE 9).  Cold pass: ``YTPU_PLAN_CACHE=0``, ``reps`` fresh engines
    each plan the prepend-fragmented fixture from scratch.  Cached pass:
    cache enabled and pre-warmed by one throwaway engine, so the same
    ``reps`` engines serve every doc from the frontier-keyed cache.
    Reports cold-vs-cached per-doc plan ms (p50/p99 across flushes), the
    cached-pass hit rate, and the Python planner's segment fast-path
    fraction on an interleaved trace."""
    import gc

    from yjs_tpu.ops import BatchEngine
    from yjs_tpu.ops import plan_cache

    update = load_prepend_fixture(n_chars)

    def one_flush() -> dict:
        eng = BatchEngine(n_docs)
        for i in range(n_docs):
            eng.queue_update(i, update)
        eng.flush()
        m = dict(eng.last_flush_metrics or {})
        del eng
        gc.collect()
        return m

    old = os.environ.get("YTPU_PLAN_CACHE")
    try:
        os.environ["YTPU_PLAN_CACHE"] = "0"
        plan_cache.reset_cache()
        cold = [one_flush() for _ in range(reps)]
        os.environ["YTPU_PLAN_CACHE"] = "1"
        plan_cache.reset_cache()
        one_flush()  # populate the cache
        cached = [one_flush() for _ in range(reps)]
    finally:
        plan_cache.reset_cache()
        if old is None:
            os.environ.pop("YTPU_PLAN_CACHE", None)
        else:
            os.environ["YTPU_PLAN_CACHE"] = old

    def pct(xs, p):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, round(p / 100 * (len(xs) - 1)))]

    cold_ms = [m["t_plan_s"] / n_docs * 1e3 for m in cold]
    cach_ms = [m["t_plan_s"] / n_docs * 1e3 for m in cached]
    hits = sum(m["plan_cache_hits"] for m in cached)
    misses = sum(m["plan_cache_misses"] for m in cached)

    # segment fast-path fraction: the Python planner on an interleaved
    # 2-client trace (the native planner plans in C++ and reports 0)
    from yjs_tpu.ops.columns import DocMirror

    trace, _ref = gen_trace(600, seed=11)
    pm = DocMirror("text")
    pm.ingest(trace, False)
    plan = pm.prepare_step()
    n_sched = len(plan.sched)
    fastpath_fraction = (
        plan.fastpath_structs / n_sched if n_sched else 0.0
    )

    res = {
        "n_docs": n_docs,
        "chars_per_doc": n_chars,
        "reps": reps,
        "cold_plan_ms_per_doc_p50": round(pct(cold_ms, 50), 3),
        "cold_plan_ms_per_doc_p99": round(pct(cold_ms, 99), 3),
        "cached_plan_ms_per_doc_p50": round(pct(cach_ms, 50), 3),
        "cached_plan_ms_per_doc_p99": round(pct(cach_ms, 99), 3),
        "plan_speedup_p50": round(
            pct(cold_ms, 50) / max(1e-9, pct(cach_ms, 50)), 2
        ),
        "cache_hit_rate": round(hits / max(1, hits + misses), 4),
        "cache_hits": hits,
        "cache_misses": misses,
        "fastpath_fraction": round(fastpath_fraction, 4),
        "fastpath_structs": plan.fastpath_structs,
        "sched_structs": n_sched,
    }
    res.update(bench_planner_cold_unique())
    res.update(bench_planner_prepend())
    try:
        with open("BENCH_planner.json", "w") as f:
            json.dump(res, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return res


def _seg_lane_env(mode: str | None):
    """Set/restore YTPU_PLAN_SEGMENT + disable the plan cache for an A/B
    lane; returns the previous values for the finally block."""
    prev = (
        os.environ.get("YTPU_PLAN_SEGMENT"),
        os.environ.get("YTPU_PLAN_CACHE"),
    )
    if mode is None:
        os.environ.pop("YTPU_PLAN_SEGMENT", None)
    else:
        os.environ["YTPU_PLAN_SEGMENT"] = mode
    return prev


def _seg_lane_restore(prev):
    for key, val in zip(("YTPU_PLAN_SEGMENT", "YTPU_PLAN_CACHE"), prev):
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val


def bench_planner_cold_unique(n_docs: int = 1024, n_ops: int = 1500) -> dict:
    """Cold-unique-frontier lane (ISSUE 15): 1024 DISTINCT traces with
    the plan cache disabled — the frontier-keyed cache cannot hit by
    construction, so the device-authoritative cold planner is the only
    accelerator.  Records ``cold_device_ms_per_doc`` (plan phase, cache
    off) and ``fastpath_residue_fraction`` (residue share of segment-
    partitioned structs), plus a cache-warm per-doc rate for the
    acceptance ratio and the ``YTPU_PLAN_SEGMENT=off`` A/B byte-identity
    verdict."""
    import gc

    from yjs_tpu.ops import BatchEngine
    from yjs_tpu.ops import plan_cache

    updates = load_distinct_traces(n_docs, n_ops)

    def one_run(mode, cache_on, prewarm=False):
        prev = _seg_lane_env(mode)
        os.environ["YTPU_PLAN_CACHE"] = "1" if cache_on else "0"
        try:
            plan_cache.reset_cache()
            if prewarm:
                w = BatchEngine(n_docs)
                for i, u in enumerate(updates):
                    w.queue_update(i, u)
                w.flush()
                np.asarray(w._right[:, 0])
                w = None
                gc.collect()
            gc.collect()
            time.sleep(2)  # let prior lane's buffer deletes drain
            eng = BatchEngine(n_docs)
            for i, u in enumerate(updates):
                eng.queue_update(i, u)
            t0 = time.perf_counter()
            eng.flush()
            np.asarray(eng._right[:, 0])
            dt = time.perf_counter() - t0
            m = dict(eng.last_flush_metrics or {})
            states = [eng.encode_state_as_update(i) for i in range(n_docs)]
            del eng
            gc.collect()
            if not cache_on:
                plan_cache.reset_cache()
            return dt, m, states
        finally:
            _seg_lane_restore(prev)

    one_run("device", cache_on=False)  # warmup/compile
    dt_dev, m_dev, s_dev = one_run("device", cache_on=False)
    _dt_off, m_off, s_off = one_run("off", cache_on=False)
    dt_warm, m_warm, _ = one_run("device", cache_on=True, prewarm=True)
    seg_f = m_dev.get("plan_segment_fast", 0)
    seg_r = m_dev.get("plan_segment_residue", 0)
    cold_ms = m_dev.get("t_plan_s", 0.0) / n_docs * 1e3
    warm_ms = m_warm.get("t_plan_s", 0.0) / n_docs * 1e3
    cold_e2e = dt_dev / n_docs * 1e3
    warm_e2e = dt_warm / n_docs * 1e3
    return {
        "cold_unique_n_docs": n_docs,
        "cold_unique_trace_ops": n_ops,
        "cold_device_ms_per_doc": round(cold_ms, 3),
        "cold_walk_ms_per_doc": round(
            m_off.get("t_plan_s", 0.0) / n_docs * 1e3, 3
        ),
        "cold_e2e_ms_per_doc": round(cold_e2e, 3),
        "warm_e2e_ms_per_doc": round(warm_e2e, 3),
        "warm_cache_plan_ms_per_doc": round(warm_ms, 3),
        # acceptance: cold distinct_engine_path within ~2x of its
        # cache-warm per-doc rate (whole-flush rate, not plan-phase-only)
        "cold_vs_warm_ratio": round(cold_e2e / max(1e-9, warm_e2e), 2),
        "fastpath_residue_fraction": round(
            seg_r / max(1, seg_f + seg_r), 4
        ),
        "plan_segment_fast": seg_f,
        "plan_segment_residue": seg_r,
        "off_lane_byte_identical": s_dev == s_off,
    }


def bench_planner_prepend(n_docs: int = 64, n_chars: int = 100000) -> dict:
    """Prepend-fragmented planner lane (ISSUE 15 bugfix pin): each doc
    is one maximally fragmented head-prepend update (one item/char).
    The monotone chain must plan without re-sorting the whole anchor
    column per flush — r5's `bench_fragmented` (default env: plan cache
    ON, 64 identical docs) measured 37.281 ms/doc; the acceptance bar
    is a >=3x drop under the SAME conditions, with harsher cache-off
    lanes alongside and the ``off`` planner lane byte-identical."""
    import gc

    from yjs_tpu.ops import BatchEngine
    from yjs_tpu.ops import plan_cache

    update = load_prepend_fixture(n_chars)

    def one_run(mode, cache_on=False):
        prev = _seg_lane_env(mode)
        os.environ["YTPU_PLAN_CACHE"] = "1" if cache_on else "0"
        try:
            plan_cache.reset_cache()
            gc.collect()
            time.sleep(2)  # let prior lane's buffer deletes drain
            eng = BatchEngine(n_docs)
            for i in range(n_docs):
                eng.queue_update(i, update)
            t0 = time.perf_counter()
            eng.flush()
            np.asarray(eng._right[:, 0])
            dt = time.perf_counter() - t0
            m = dict(eng.last_flush_metrics or {})
            state = eng.encode_state_as_update(0)
            del eng
            gc.collect()
            plan_cache.reset_cache()
            return dt, m, state
        finally:
            _seg_lane_restore(prev)

    _ = one_run("device")  # warmup/compile
    dt_dev, m_dev, s_dev = one_run("device")
    _dt_off, m_off, s_off = one_run("off")
    _dt_r5, m_r5, _ = one_run("device", cache_on=True)  # r5-parity lane
    dev_ms = m_dev.get("t_plan_s", 0.0) / n_docs * 1e3
    off_ms = m_off.get("t_plan_s", 0.0) / n_docs * 1e3
    r5p_ms = m_r5.get("t_plan_s", 0.0) / n_docs * 1e3
    return {
        "prepend_n_docs": n_docs,
        "prepend_chars_per_doc": n_chars,
        # r5-parity conditions (plan cache on, bench_fragmented shape):
        # the acceptance comparison against BENCH_local_r5.json's
        # planner_ms_per_doc = 37.281
        "prepend_planner_ms_per_doc": round(r5p_ms, 3),
        "prepend_r5_baseline_ms_per_doc": 37.281,
        "prepend_speedup_vs_r5": round(37.281 / max(1e-9, r5p_ms), 2),
        # harsher cache-off lanes: every doc plans cold
        "prepend_cold_ms_per_doc": round(dev_ms, 3),
        "prepend_cold_walk_ms_per_doc": round(off_ms, 3),
        "prepend_cold_speedup_vs_walk": round(
            off_ms / max(1e-9, dev_ms), 2
        ),
        "prepend_off_lane_byte_identical": s_dev == s_off,
    }


def bench_flush(
    n_docs: int = 32, warmup_ops: int = 800, ops_per_round: int = 40,
    rounds: int = 4, chunk: int = 4,
) -> dict:
    """detail.flush → BENCH_flush.json: pipelined flush effectiveness
    (ISSUE 12).  A/B on the same batched text workload — ``n_docs``
    continuing editors, ``rounds`` incremental flush rounds each, with
    ``YTPU_FLUSH_CHUNK`` shrunk so every flush runs n_docs/chunk staged
    chunks and stage N+1's host pack can overlap stage N's device
    execution.  Round 0 is the allocating warm-up; rounds 1+ are steady
    state, where donation should eliminate reallocation entirely.
    Reports the steady-state overlap fraction, donated-vs-realloc
    bytes, pipelined host time (pack + honest device wait) against the
    synchronous path's t_total, and the adaptive flush-tick p50/p99
    batch window from a scripted busy/idle/burn drive."""
    import gc

    import yjs_tpu as Y
    from yjs_tpu.ops import BatchEngine
    from yjs_tpu.ops import plan_cache
    from yjs_tpu.provider import TpuProvider

    def editor_rounds(seed: int) -> list[bytes]:
        """``rounds`` incremental update batches from one continuing
        seeded editor.  Round 0 is a big warm-up (sizes the device
        tables once); later rounds are small steady-state edit batches
        that fit the warmed capacity, so they measure donation, not
        growth."""
        gen = random.Random(seed)
        d = Y.Doc(gc=False)
        d.client_id = 500 + seed
        t = d.get_text("text")
        out = []
        for r in range(rounds):
            sv = Y.encode_state_vector(d)
            for _ in range(warmup_ops if r == 0 else ops_per_round):
                if len(t) and gen.random() < 0.2:
                    t.delete(gen.randrange(len(t)), 1)
                else:
                    t.insert(gen.randrange(len(t) + 1),
                             gen.choice("abcdef "))
            out.append(Y.encode_state_as_update(d, sv))
        return out

    traces = [editor_rounds(7000 + i) for i in range(n_docs)]

    def drive(pipeline: bool) -> list[dict]:
        plan_cache.reset_cache()
        os.environ["YTPU_FLUSH_PIPELINE"] = "1" if pipeline else "0"
        eng = BatchEngine(n_docs)
        out = []
        for r in range(rounds):
            for i in range(n_docs):
                eng.queue_update(i, traces[i][r])
            eng.flush()
            out.append(dict(eng.last_flush_metrics or {}))
        del eng
        gc.collect()
        return out

    saved = {
        k: os.environ.get(k)
        for k in ("YTPU_FLUSH_PIPELINE", "YTPU_FLUSH_CHUNK")
    }
    try:
        os.environ["YTPU_FLUSH_CHUNK"] = str(chunk)
        drive(pipeline=True)  # jit compile warm-up: neither mode pays it
        sync_ms = drive(pipeline=False)
        pipe_ms = drive(pipeline=True)
    finally:
        plan_cache.reset_cache()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    steady = pipe_ms[1:]
    pack_s = sum(m["t_pack_s"] for m in steady)
    overlap_s = sum(m["t_pack_overlap_s"] for m in steady)
    wait_s = sum(m["t_device_wait_s"] for m in steady)
    sync_total_s = sum(m["t_total_s"] for m in sync_ms[1:])
    pipe_host_s = pack_s + wait_s

    # adaptive flush tick: scripted busy/idle/burn drive with injected
    # timestamps (deterministic p50/p99 of the applied batch windows)
    prov = TpuProvider(4)
    d = Y.Doc(gc=False)
    gen = random.Random(99)
    now = 0.0
    for step in range(120):
        now += 0.004
        if step % 3 != 2:  # two busy ticks, then an idle one
            sv = Y.encode_state_vector(d)
            d.get_text("text").insert(0, gen.choice("abcdef"))
            prov.receive_update("room", Y.encode_state_as_update(d, sv))
        prov.flush_tick(now=now)
    ticks = prov.flush_ticks.percentiles()

    res = {
        "n_docs": n_docs,
        "warmup_ops": warmup_ops,
        "ops_per_round": ops_per_round,
        "rounds": rounds,
        "flush_chunk": chunk,
        "chunks_per_flush": n_docs // chunk,
        # steady-state pipeline quality
        "overlap_fraction": round(overlap_s / max(1e-9, pack_s), 4),
        "donation_hit_rate": round(
            sum(m["flush_donated"] for m in steady) / max(1, len(steady)),
            4,
        ),
        "realloc_bytes_warmup": pipe_ms[0]["realloc_bytes"],
        "realloc_bytes_steady": sum(m["realloc_bytes"] for m in steady),
        "pipeline_depth_max": max(m["pipeline_depth"] for m in pipe_ms),
        # A/B: pipelined host cost vs the synchronous path's wall time
        "pipe_pack_s": round(pack_s, 6),
        "pipe_device_wait_s": round(wait_s, 6),
        "pipe_host_s": round(pipe_host_s, 6),
        "sync_total_s": round(sync_total_s, 6),
        "pipe_host_lt_sync_total": bool(pipe_host_s < sync_total_s),
        # adaptive tick distribution under the scripted drive
        "tick_window_p50_ms": ticks["p50_ms"],
        "tick_window_p99_ms": ticks["p99_ms"],
    }
    try:
        with open("BENCH_flush.json", "w") as f:
            json.dump(res, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return res


# ---------------------------------------------------------------------------
# Variant 3: batched sync step 2 (state-vector diff) over all distinct docs
# ---------------------------------------------------------------------------


# isolated-measurement band for sync_step2_batched at 1024 docs on this
# host (BASELINE.md r5: 5 isolated reps measured 7.3-8.0k/s; r3 recorded
# 7.6-8.7k in its sessions).  Single-window readings below the floor
# indicate harness contention (cleanup RPCs / tunnel weather), not a
# code regression.
_SYNC_BAND = (7300.0, 8000.0)


def bench_sync(eng, n_docs: int) -> dict:
    # every doc answers a fresh peer (empty SV -> full-state diff): one
    # diff_mask_kernel dispatch + per-doc native wire encode.  First call
    # warms the kernel compile; median of 3 windows (single windows read
    # up to ~40% low when the distinct loop's cleanup RPCs are still
    # draining — the r4 "regression" was exactly this, BASELINE.md r5).
    requests = [(i, {}) for i in range(n_docs)]
    eng.sync_step2_batch(requests)
    windows = []
    for _ in range(3):
        t0 = time.perf_counter()
        replies = eng.sync_step2_batch(requests)
        windows.append(time.perf_counter() - t0)
    dt = sorted(windows)[1]
    total_bytes = sum(len(r) for r in replies)
    rate = n_docs / dt
    out = {
        "n_docs": n_docs,
        "syncs_per_sec": round(rate, 1),
        "encoded_mb_per_sec": round(total_bytes / dt / 1e6, 2),
        "t_total_s": round(dt, 4),
    }
    if n_docs == 1024 and rate < _SYNC_BAND[0] * 0.7:
        out["band_warning"] = (
            f"rate {rate:.0f}/s is >30% below the isolated band "
            f"{_SYNC_BAND} — suspect harness/tunnel contention first "
            "(see BASELINE.md r5)"
        )
    return out


def sweep_distinct(n_ops: int, sizes=(1024, 2048, 4096, 8192)) -> list[dict]:
    """Distinct-doc scaling sweep (VERDICT r4 item 2): per-phase timers at
    growing doc counts feed the 100k-doc extrapolation in BASELINE.md.
    Opt-in (YTPU_BENCH_SWEEP=1) — it multiplies the bench runtime."""
    rows = []
    for n in sizes:
        d, eng = bench_distinct(n, n_ops, runs=3)
        rows.append(d)
        del eng
        print(json.dumps({"sweep_row": d}), file=sys.stderr, flush=True)
        time.sleep(3)
    return rows


def write_obs_artifacts(eng) -> dict:
    """Persist the headline engine's observability state: the full
    metrics snapshot JSON + a Perfetto-loadable Chrome trace
    (YTPU_BENCH_OBS_PREFIX names them, default BENCH_obs_*).  Returns the
    inline per-phase summary for the bench result — plan_threads,
    schedule occupancy, per-phase p50 seconds — and never fails the
    bench on a write error (obs is diagnostics, not the measurement)."""
    out: dict = {}
    try:
        prefix = os.environ.get("YTPU_BENCH_OBS_PREFIX", "BENCH_obs")
        snap = eng.metrics_snapshot()
        m = eng.last_flush_metrics or {}
        phase = snap.get("histograms", {}).get(
            "ytpu_engine_phase_seconds", {}
        )
        out = {
            "plan_threads": m.get("plan_threads", 1),
            "schedule_occupancy": round(m.get("schedule_occupancy", 0.0), 4),
            "phase_seconds_p50": {
                k.split("=", 1)[1]: round(v.get("p50", 0.0), 6)
                for k, v in phase.items()
            },
            "flushes_recorded": snap.get("n_flushes_recorded", 0),
        }
        metrics_path = f"{prefix}_metrics.json"
        with open(metrics_path, "w") as f:
            json.dump(snap, f)
        out["metrics_path"] = metrics_path
        out["trace_path"] = eng.save_trace(f"{prefix}_trace.json")
        out["trace_events"] = len(eng.obs.tracer)
    except Exception as e:  # pragma: no cover - diagnostics only
        out["error"] = repr(e)
    return out


def bench_resilience(n_ops: int = 200) -> dict:
    """Failure-isolation overhead: the same flush clean vs with one
    poisoned doc.  The rollback path (validate the update log, strip the
    bad bytes to the dead-letter queue, replay the survivors into a CPU
    doc) bills only the failing doc — the other n-1 docs should pay
    nothing measurable."""
    import gc

    from yjs_tpu.ops import BatchEngine

    n_docs = int(os.environ.get("YTPU_BENCH_RESILIENCE_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)
    bad = n_docs // 2
    poison = b"\xff\xff\xff\xff\xff"

    def run(poisoned: bool, runs: int = 3):
        times, snap = [], None
        for _ in range(runs):
            gc.collect()
            eng = BatchEngine(n_docs)
            t0 = time.perf_counter()
            for i, u in enumerate(updates):
                eng.queue_update(i, u)
            if poisoned:
                eng.queue_update(bad, poison)
            eng.flush()
            np.asarray(eng._right[:, 0])
            times.append(time.perf_counter() - t0)
            snap = eng.resilience_snapshot()
            eng = None
        times.sort()
        return times[len(times) // 2], snap

    t_clean, _ = run(False)  # also warms the compile cache
    t_poison, snap = run(True)
    return {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "clean_flush_s": round(t_clean, 4),
        "poisoned_flush_s": round(t_poison, 4),
        "isolation_overhead_s": round(t_poison - t_clean, 4),
        "isolation_overhead_pct": (
            round(100 * (t_poison - t_clean) / t_clean, 1) if t_clean else 0
        ),
        "snapshot": snap,
    }


def bench_durability(n_ops: int = 200) -> dict:
    """WAL flush-path overhead: the same per-doc ingest+flush with the
    journal off, on with ``fsync=never`` (journaling cost alone: encode
    + CRC + buffered write), and on with ``fsync=always`` (worst-case
    durable mode — one disk round trip per update)."""
    import gc
    import shutil
    import tempfile

    from yjs_tpu.persistence import WalConfig
    from yjs_tpu.provider import TpuProvider

    n_docs = int(os.environ.get("YTPU_BENCH_WAL_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)

    def run(fsync: str | None, runs: int = 3) -> float:
        times = []
        for _ in range(runs):
            gc.collect()
            wal_dir = tempfile.mkdtemp(prefix="ytpu-bench-wal-")
            try:
                prov = TpuProvider(
                    n_docs,
                    wal_dir=wal_dir if fsync else None,
                    wal_config=WalConfig(fsync=fsync) if fsync else None,
                )
                t0 = time.perf_counter()
                for i, u in enumerate(updates):
                    prov.receive_update(f"room-{i}", u)
                prov.flush()
                np.asarray(prov.engine._right[:, 0])
                times.append(time.perf_counter() - t0)
                prov = None
            finally:
                shutil.rmtree(wal_dir, ignore_errors=True)
        times.sort()
        return times[len(times) // 2]

    t_off = run(None)  # also warms the compile cache
    t_never = run("never")
    t_always = run("always")
    return {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "wal_off_s": round(t_off, 4),
        "wal_never_s": round(t_never, 4),
        "wal_always_s": round(t_always, 4),
        "journal_overhead_pct": (
            round(100 * (t_never - t_off) / t_off, 1) if t_off else 0
        ),
        "fsync_overhead_pct": (
            round(100 * (t_always - t_off) / t_off, 1) if t_off else 0
        ),
    }


def bench_obs_prof(n_ops: int = 200) -> dict:
    """Profiler/SLO overhead: the same per-doc ingest+flush with the obs
    stack live (kernel profiler, convergence tracker, registries) vs
    fully disabled (``YTPU_OBS_DISABLED=1``).  The ISSUE-4 budget is
    <=3% with ``YTPU_PROF_DEVICE`` unset; the compile-cache hit rates
    from the live run show the attribution actually worked."""
    import gc

    from yjs_tpu.obs.prof import kernel_profiler
    from yjs_tpu.provider import TpuProvider

    n_docs = int(os.environ.get("YTPU_BENCH_PROF_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)

    def run(disabled: bool, runs: int = 3) -> float:
        times = []
        prior = os.environ.pop("YTPU_OBS_DISABLED", None)
        if disabled:
            os.environ["YTPU_OBS_DISABLED"] = "1"
        try:
            for _ in range(runs):
                gc.collect()
                prov = TpuProvider(n_docs)
                t0 = time.perf_counter()
                for i, u in enumerate(updates):
                    prov.receive_update(f"room-{i}", u)
                prov.flush()
                np.asarray(prov.engine._right[:, 0])
                times.append(time.perf_counter() - t0)
                prov = None
        finally:
            if prior is None:
                os.environ.pop("YTPU_OBS_DISABLED", None)
            else:
                os.environ["YTPU_OBS_DISABLED"] = prior
        times.sort()
        return times[len(times) // 2]

    t_off = run(True)  # also warms the compile cache
    t_on = run(False)
    prof = kernel_profiler().snapshot()
    hit_rates = {
        k: v["hit_rate"] for k, v in sorted(prof["kernels"].items())
    }
    return {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "obs_on_s": round(t_on, 4),
        "obs_off_s": round(t_off, 4),
        "overhead_pct": (
            round(100 * (t_on - t_off) / t_off, 1) if t_off else 0
        ),
        "compile_cache_hit_rates": hit_rates,
        "retrace_events": len(prof["retrace_events"]),
    }


def bench_obs_dist(n_ops: int = 200) -> dict:
    """Distributed-tracing overhead (ISSUE 11): the same per-doc
    ingest+flush with the causal-tracing stack live at the default
    head-sample rate (trace minting at ingress, contextvar propagation,
    SLO flow stamping, flight recorder) vs the obs stack fully disabled
    (``YTPU_OBS_DISABLED=1``).  The budget is <=3% end-to-end at the
    default ``YTPU_TRACE_SAMPLE`` — tracing identity is one keyed
    blake2b per update, everything else rides seams that already
    existed."""
    import gc

    from yjs_tpu.obs.blackbox import flight_recorder
    from yjs_tpu.obs.dist import sample_rate
    from yjs_tpu.provider import TpuProvider

    n_docs = int(os.environ.get("YTPU_BENCH_PROF_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)

    def run(disabled: bool, runs: int = 3) -> float:
        times = []
        prior = os.environ.pop("YTPU_OBS_DISABLED", None)
        if disabled:
            os.environ["YTPU_OBS_DISABLED"] = "1"
        try:
            for _ in range(runs):
                gc.collect()
                prov = TpuProvider(n_docs)
                t0 = time.perf_counter()
                for i, u in enumerate(updates):
                    prov.receive_update(f"room-{i}", u)
                prov.flush()
                np.asarray(prov.engine._right[:, 0])
                times.append(time.perf_counter() - t0)
                prov = None
        finally:
            if prior is None:
                os.environ.pop("YTPU_OBS_DISABLED", None)
            else:
                os.environ["YTPU_OBS_DISABLED"] = prior
        times.sort()
        return times[len(times) // 2]

    t_off = run(True)  # also warms the compile cache
    t_on = run(False)
    return {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "sample_rate": sample_rate(),
        "tracing_on_s": round(t_on, 4),
        "obs_off_s": round(t_off, 4),
        "overhead_pct": (
            round(100 * (t_on - t_off) / t_off, 1) if t_off else 0
        ),
        "blackbox": flight_recorder().stats(),
    }


def bench_obs_admin(n_ops: int = 200) -> dict:
    """detail.obs_admin → BENCH_obs_admin.json: admin-plane overhead
    (ISSUE 16).  The same per-doc ingest+flush hot path twice — no
    admin server vs an embedded :class:`AdminServer` being scraped at
    a realistic cadence (one endpoint every 250ms, rotating through
    /metrics, /metrics.json, /statusz, /readyz — a 1s-interval
    Prometheus scrape plus probes, still an order of magnitude hotter
    than a production 15s scrape) from a background thread.  The
    budget is <1% end-to-end: the plane is a daemon thread that only
    wakes when a request arrives, and the registry reads it serves are
    lock-free snapshots."""
    import gc
    import threading
    import urllib.request

    from yjs_tpu.obs.admin import AdminServer
    from yjs_tpu.provider import TpuProvider

    from yjs_tpu.core import Doc
    from yjs_tpu.updates import encode_state_as_update

    n_docs = int(os.environ.get("YTPU_BENCH_PROF_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)
    # enough rounds that a run spans several scrape intervals — the
    # one-shot ingest+flush shape finishes in single-digit ms, which
    # would time a plane nobody ever scraped
    rounds = int(os.environ.get("YTPU_BENCH_ADMIN_ROUNDS", "600"))
    edits_per_round = 8
    scrape_interval_s = 0.25
    endpoints = ("/metrics", "/metrics.json", "/statusz", "/readyz")
    scrapes = {"n": 0}

    # fresh per-round edit payloads, pre-encoded so payload synthesis
    # is outside both timed loops
    round_edits = [
        encode_state_as_update(
            (d := Doc(gc=False),
             d.get_text("text").insert(0, f"edit {k} "))[0]
        )
        for k in range(edits_per_round)
    ]

    def run(with_admin: bool, runs: int = 3) -> float:
        times = []
        for _ in range(runs):
            gc.collect()
            prov = TpuProvider(n_docs)
            # seed every room once so the steady-state loop measures
            # incremental merges, not first-touch allocation
            for i, u in enumerate(updates):
                prov.receive_update(f"room-{i}", u)
            prov.flush()
            admin = scraper = None
            stop = threading.Event()
            if with_admin:
                admin = AdminServer(prov, role="provider").start()

                def scrape_loop():
                    k = 0
                    while not stop.wait(scrape_interval_s):
                        try:
                            req = urllib.request.urlopen(
                                admin.url + endpoints[k % len(endpoints)],
                                timeout=5,
                            )
                            with req as r:
                                r.read()
                            scrapes["n"] += 1
                        except OSError:
                            pass  # teardown race; the timing loop owns exit
                        k += 1

                scraper = threading.Thread(target=scrape_loop, daemon=True)
                scraper.start()
            t0 = time.perf_counter()
            for r in range(rounds):
                for k, u in enumerate(round_edits):
                    prov.receive_update(
                        f"room-{(r * edits_per_round + k) % n_docs}", u
                    )
                prov.flush()
            np.asarray(prov.engine._right[:, 0])
            times.append(time.perf_counter() - t0)
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5)
            if admin is not None:
                admin.close()
            prov.close()
        times.sort()
        return times[len(times) // 2]

    t_off = run(False)  # also warms the compile cache
    t_on = run(True)
    block = {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "rounds": rounds,
        "edits_per_round": edits_per_round,
        "scrape_interval_s": scrape_interval_s,
        "scrapes_served": scrapes["n"],
        "admin_on_s": round(t_on, 4),
        "admin_off_s": round(t_off, 4),
        "overhead_pct": (
            round(100 * (t_on - t_off) / t_off, 1) if t_off else 0
        ),
    }
    try:
        with open("BENCH_obs_admin.json", "w") as f:
            json.dump(block, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return block


def bench_obs_tsdb(n_ops: int = 200) -> dict:
    """detail.obs_tsdb → BENCH_obs_tsdb.json: embedded-TSDB sampler +
    cost-ledger overhead (ISSUE 19).  Every doc stages an edit each
    round so the flush does representative engine work, with the
    sampler cranked to a 250ms cadence (20x hotter than the 5s
    default).  ``overhead_pct`` — the <1%-budget headline — is
    INSTRUMENTED at the telemetry seams: each obs seam (per-ingress
    ``staged`` hook, per-flush epoch enqueue + batched distribution,
    sampler tick) is unit-priced in a tight post-run loop against the
    run's own loaded state and charged at its exact live call count;
    the sum over the run's wall clock is the figure.  A
    disabled-vs-enabled wall-clock diff is reported alongside as
    ``ab_overhead_pct``, but on a shared host its scheduler noise
    floor (±10% run-to-run on this workload) swamps a sub-percent
    signal, so it is informational only."""
    import gc
    import importlib

    # yjs_tpu.obs re-exports the tsdb() accessor under the same name, so a
    # plain ``import yjs_tpu.obs.tsdb`` binds the function — load the module.
    tsdb_mod = importlib.import_module("yjs_tpu.obs.tsdb")
    from yjs_tpu.provider import TpuProvider

    from yjs_tpu.core import Doc
    from yjs_tpu.updates import encode_state_as_update

    n_docs = int(os.environ.get("YTPU_BENCH_PROF_DOCS", "64"))
    updates = load_distinct_traces(n_docs, n_ops)
    rounds = int(os.environ.get("YTPU_BENCH_TSDB_ROUNDS", "150"))
    edits_per_round = n_docs  # every doc stages each round
    sample_interval_s = 0.25

    round_edits = [
        encode_state_as_update(
            (d := Doc(gc=False),
             d.get_text("text").insert(0, f"edit {k} "))[0]
        )
        for k in range(edits_per_round)
    ]

    def fresh_store() -> None:
        # the store is a process-global singleton: park the old one and
        # let the next enabled provider construct a fresh store that
        # reads the bench cadence from the env
        with tsdb_mod._TSDB_GUARD:
            old, tsdb_mod._TSDB = tsdb_mod._TSDB, None
        if old is not None:
            old.close()

    saved = {
        k: os.environ.get(k)
        for k in ("YTPU_TSDB_DISABLED", "YTPU_COST_DISABLED",
                  "YTPU_TSDB_INTERVAL_S")
    }
    stats = {}
    # instrumented seconds inside the obs seams: [flush, staged, sampler]
    obs_spent = [0.0, 0.0, 0.0]

    def run_once(enabled: bool, instrument: bool = False) -> float:
        gc.collect()
        if enabled:
            os.environ.pop("YTPU_TSDB_DISABLED", None)
            os.environ.pop("YTPU_COST_DISABLED", None)
            os.environ["YTPU_TSDB_INTERVAL_S"] = str(sample_interval_s)
        else:
            os.environ["YTPU_TSDB_DISABLED"] = "1"
            os.environ["YTPU_COST_DISABLED"] = "1"
        fresh_store()
        prov = TpuProvider(n_docs)
        if instrument:
            store = tsdb_mod.tsdb()
        for i, u in enumerate(updates):
            prov.receive_update(f"bench/room-{i}", u)
        prov.flush()
        ticks_before = (
            int(tsdb_mod.tsdb().stats().get("samples", 0))
            if instrument else 0
        )
        t0 = time.perf_counter()
        for r in range(rounds):
            for k, u in enumerate(round_edits):
                prov.receive_update(
                    f"bench/room-{(r * edits_per_round + k) % n_docs}",
                    u,
                )
            prov.flush()
        np.asarray(prov.engine._right[:, 0])
        dt = time.perf_counter() - t0
        if enabled:
            stats.update(tsdb_mod.tsdb().stats())
        if instrument:
            # charge the ingress hook by measured unit price x the
            # exact number of timed-loop calls (one per accepted edit);
            # guids are prebuilt — the live caller passes an existing
            # string, so formatting is harness cost, not hook cost
            # every obs seam is priced the same way: a tight post-run
            # loop measures the unit cost against the run's own loaded
            # state, and the seam is charged unit price x its exact
            # live call count.  Min over batches rejects GC / scheduler
            # spikes landing inside a pricing loop; each batch is long
            # enough that amortized costs (chunk seals, settling
            # drains) are represented at their true duty cycle.
            n_calls = 10_000
            price_guids = [f"bench/room-{i % n_docs}" for i in range(n_calls)]
            per_staged = None
            for _ in range(2):
                tp0 = time.perf_counter()
                for g in price_guids:
                    prov.cost.staged(g, 40)
                dt_batch = (time.perf_counter() - tp0) / n_calls
                per_staged = (
                    dt_batch if per_staged is None
                    else min(per_staged, dt_batch)
                )
            obs_spent[1] += per_staged * rounds * edits_per_round
            # charge the flush seam (epoch enqueue + its share of the
            # batched distribution) at the post-run unit price: each
            # pricing batch re-stages every doc and runs one full
            # settling drain, exactly the live duty cycle
            fm = prov.engine.last_flush_metrics
            batch = 32  # = cost._DRAIN_EVERY epochs -> one drain each
            per_flush = None
            for _ in range(3):
                spent = 0.0
                for _ in range(batch):
                    for g in price_guids[:n_docs]:
                        prov.cost.staged(g, 40)
                    tp0 = time.perf_counter()
                    prov.cost.on_flush(fm)
                    spent += time.perf_counter() - tp0
                spent /= batch
                per_flush = (
                    spent if per_flush is None else min(per_flush, spent)
                )
            obs_spent[0] += per_flush * rounds
            # charge the sampler by measured per-tick price (walking
            # the same loaded registries, synchronously) x the ticks
            # that fired inside the timed window
            ticks = int(stats.get("samples", 0)) - ticks_before
            per_tick = None
            for _ in range(3):
                tp0 = time.perf_counter()
                for _ in range(5):
                    store.sample_once()
                dt_batch = (time.perf_counter() - tp0) / 5
                per_tick = (
                    dt_batch if per_tick is None
                    else min(per_tick, dt_batch)
                )
            obs_spent[2] += per_tick * ticks
        prov.close()
        return dt

    try:
        run_once(False)  # warms the compile cache
        t_offs, t_ons = [], []
        for _ in range(2):  # alternate off/on so drift hits both sides
            t_offs.append(run_once(False))
            t_ons.append(run_once(True))
        t_off, t_on = min(t_offs), min(t_ons)
        obs_spent[:] = [0.0, 0.0, 0.0]
        t_inst = run_once(True, instrument=True)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        fresh_store()
    block = {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "rounds": rounds,
        "edits_per_round": edits_per_round,
        "sample_interval_s": sample_interval_s,
        "samples": int(stats.get("samples", 0)),
        "series": int(stats.get("series", 0)),
        "points_raw": int(stats.get("points_raw", 0)),
        "encoded_bytes": int(stats.get("encoded_bytes", 0)),
        "tsdb_on_s": round(t_on, 4),
        "tsdb_off_s": round(t_off, 4),
        "obs_seconds": round(sum(obs_spent), 4),
        "obs_flush_s": round(obs_spent[0], 4),
        "obs_staged_s": round(obs_spent[1], 4),
        "obs_sampler_s": round(obs_spent[2], 4),
        "instrumented_wall_s": round(t_inst, 4),
        "budget_pct": 1.0,
        "overhead_pct": (
            round(100 * sum(obs_spent) / t_inst, 2) if t_inst else 0
        ),
        "ab_overhead_pct": (
            round(100 * (t_on - t_off) / t_off, 1) if t_off else 0
        ),
    }
    try:
        with open("BENCH_obs_tsdb.json", "w") as f:
            json.dump(block, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return block


def bench_capacity() -> dict:
    """detail.capacity → BENCH_capacity.json: sessions-per-device at
    interactive SLO (ISSUE 19, the ROADMAP's capacity-planning number).
    Ramps all-interactive loadgen sessions against fresh providers
    until the wall-clock convergence SLO verdict (or the visibility-p99
    tick budget) degrades; the published knee is read back from the
    embedded TSDB's history of the ramp, not from a side variable —
    the figure and the query path are tested together."""
    import gc

    from yjs_tpu.obs.capacity import (
        CapacityConfig,
        ramp_capacity,
        sessions_per_device,
    )
    from yjs_tpu.obs.tsdb import Tsdb, TsdbConfig
    from yjs_tpu.provider import TpuProvider

    gc.collect()
    cfg = CapacityConfig(
        start_sessions=int(os.environ.get("YTPU_BENCH_CAP_START", "8")),
        max_sessions=int(os.environ.get("YTPU_BENCH_CAP_MAX", "192")),
        ticks_per_stage=int(os.environ.get("YTPU_BENCH_CAP_TICKS", "24")),
        slo_target_ms=float(
            os.environ.get("YTPU_BENCH_CAP_SLO_MS", "1000")
        ),
        seed=0,
    )
    # a private store so earlier bench blocks' sampler history cannot
    # alias the ramp series the knee is read from
    store = Tsdb(TsdbConfig(interval_s=5.0, directory=None))

    def make_server(n_sessions: int):
        return TpuProvider(n_sessions + 8)

    result = ramp_capacity(make_server, cfg, store=store)
    block = sessions_per_device(result)
    block.update({
        "slo_target_ms": cfg.slo_target_ms,
        "ticks_per_stage": cfg.ticks_per_stage,
        "p99_limit_ticks": result["p99_limit_ticks"],
        "stages": result["stages"],
    })
    try:
        with open("BENCH_capacity.json", "w") as f:
            json.dump(block, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return block


def bench_network(n_ops: int = 200) -> dict:
    """Session-layer cost (ISSUE 5): the same cross-provider fan-out
    through per-room :class:`SyncSession` pairs over an in-memory pipe,
    once on a clean wire and once through the network fault injector
    (drop + dup + reorder) — the lossy run's extra wall time is what
    ack/retransmit + anti-entropy pay to still converge exactly."""
    import gc

    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
    from yjs_tpu.sync import PipeNetwork, SessionConfig

    n_docs = int(os.environ.get("YTPU_BENCH_NET_DOCS", "16"))
    updates = load_distinct_traces(n_docs, n_ops)
    # retry_base must exceed the pipe's 2-round ack RTT or every frame
    # retransmits once "spuriously"; idle_rounds must outlast the worst
    # backoff gap (retry_cap * (1+jitter)) so settle keeps ticking
    # through droughts where every in-flight copy was dropped.
    # anti-entropy stays OFF: its digest cadence keeps the wire busy
    # forever, so settle would never idle out and the rounds delta
    # (the recovery-cost number this bench reports) would be noise —
    # retransmission alone owns loss recovery here
    cfg = SessionConfig(
        heartbeat=0, liveness=0, antientropy=0, retry_base=4,
        retry_cap=16, seed=11,
    )

    def run(injector) -> dict:
        gc.collect()
        a = TpuProvider(n_docs)
        b = TpuProvider(n_docs)
        net = PipeNetwork(injector)
        for i in range(n_docs):
            t1, t2 = net.pair()
            a.session(f"room-{i}", "b", cfg).connect(t1)
            b.session(f"room-{i}", "a", cfg).connect(t2)

        def drive():
            a.flush()
            b.flush()
            a.tick_sessions()
            b.tick_sessions()

        t0 = time.perf_counter()
        net.settle((drive,))
        for i, u in enumerate(updates):
            a.receive_update(f"room-{i}", u)
        rounds = net.settle((drive,), max_rounds=5000, idle_rounds=40)
        dt = time.perf_counter() - t0
        converged = all(
            a.text(f"room-{i}") == b.text(f"room-{i}")
            for i in range(n_docs)
        )
        rows = a.sessions_snapshot() + b.sessions_snapshot()
        return {
            "elapsed_s": round(dt, 4),
            "rounds": rounds,
            "converged": converged,
            "frames_sent": sum(r["sent"] for r in rows),
            "retransmits": sum(r["retransmits"] for r in rows),
            "repairs": sum(r["repairs"] for r in rows),
            "dead_lettered": sum(r["dead_lettered"] for r in rows),
        }

    clean = run(None)
    lossy = run(
        NetworkFaultInjector(
            NetChaosConfig(
                seed=11, drop=0.1, duplicate=0.05, reorder=0.2
            )
        )
    )
    return {
        "n_docs": n_docs,
        "trace_ops": n_ops,
        "clean": clean,
        "lossy": lossy,
        # round-based (deterministic): wall time mixes in flush JIT
        # warmup, which the clean run pays for both
        "loss_recovery_overhead_rounds": lossy["rounds"] - clean["rounds"],
    }


def bench_fleet(n_ops: int = 200) -> dict:
    """Fleet routing + live-migration cost (ISSUE 6), two parts:

    - **simulated scale**: 100k docs placed onto N simulated shard
      devices through the bare bounded-load ring (per-shard loads as
      plain arrays) — placement throughput, docs-per-shard spread, and
      the reassignment churn of draining one shard (the consistent-hash
      minimal-movement contract, measured not assumed);
    - **real migration**: a small live fleet timing ``migrate_doc`` end
      to end (intent journal + export + apply + release + epoch bump) —
      migrations/s and the p50/p99 stall a doc sees while moving.

    The block is also written to BENCH_fleet.json.
    """
    import gc

    from yjs_tpu.fleet import FleetRouter, HashRing

    n_sim = int(os.environ.get("YTPU_BENCH_FLEET_DOCS", "100000"))
    n_shards = int(os.environ.get("YTPU_BENCH_FLEET_SHARDS", "8"))

    ring = HashRing(range(n_shards), vnodes=64)
    cap = max(1, (2 * n_sim) // n_shards)
    loads = [0] * n_shards
    owners = [0] * n_sim
    shed = 0
    t0 = time.perf_counter()
    for i in range(n_sim):
        s, did_shed = ring.place(
            f"doc-{i}", loads.__getitem__, lambda _s: cap, 1.25
        )
        loads[s] += 1
        owners[i] = s
        if did_shed:
            shed += 1
    place_dt = time.perf_counter() - t0
    spread = {
        "min": min(loads),
        "max": max(loads),
        "mean": round(n_sim / n_shards, 1),
        # 1.0 = perfectly even; the bounded-load ceiling caps this at
        # ~the configured load factor
        "imbalance": round(max(loads) * n_shards / n_sim, 3),
    }

    # drain churn: retire one shard and re-place ONLY its docs
    victim = n_shards - 1
    ring.remove(victim)
    to_move = [i for i in range(n_sim) if owners[i] == victim]
    t1 = time.perf_counter()
    for i in to_move:
        s, _ = ring.place(
            f"doc-{i}", loads.__getitem__, lambda _s: cap, 1.25,
            exclude={victim},
        )
        loads[victim] -= 1
        loads[s] += 1
        owners[i] = s
    drain_dt = time.perf_counter() - t1

    # -- real fleet: live migration latency --------------------------------
    gc.collect()
    n_docs = int(os.environ.get("YTPU_BENCH_FLEET_MIG_DOCS", "24"))
    updates = load_distinct_traces(n_docs, n_ops)
    fleet = FleetRouter(4, n_docs)
    for i, u in enumerate(updates):
        fleet.receive_update(f"room-{i}", u)
    fleet.flush()
    # one untimed round trip warms the export/apply compile caches
    warm_src = fleet.shard_of("room-0")
    fleet.migrate_doc("room-0", (warm_src + 1) % 4)
    fleet.migrate_doc("room-0", warm_src)
    stalls_ms = []
    t2 = time.perf_counter()
    for i in range(n_docs):
        g = f"room-{i}"
        dst = (fleet.shard_of(g) + 1) % 4
        m0 = time.perf_counter()
        fleet.migrate_doc(g, dst)
        stalls_ms.append((time.perf_counter() - m0) * 1000.0)
    mig_dt = time.perf_counter() - t2
    converged = all(
        fleet.text(f"room-{i}") is not None for i in range(n_docs)
    )
    stalls_ms.sort()

    def pct(p):
        return round(stalls_ms[min(len(stalls_ms) - 1,
                                   int(p * len(stalls_ms)))], 3)

    out = {
        "sim": {
            "n_docs": n_sim,
            "n_shards": n_shards,
            "placements_per_sec": (
                round(n_sim / place_dt, 1) if place_dt else 0.0
            ),
            "docs_per_shard": spread,
            "shed_placements": shed,
            "drain_moved_docs": len(to_move),
            "drain_churn_fraction": round(len(to_move) / n_sim, 4),
            "drain_replace_per_sec": (
                round(len(to_move) / drain_dt, 1) if drain_dt else 0.0
            ),
        },
        "migration": {
            "n_docs": n_docs,
            "n_shards": 4,
            "trace_ops": n_ops,
            "migrations_per_sec": (
                round(n_docs / mig_dt, 1) if mig_dt else 0.0
            ),
            "stall_ms_p50": pct(0.50),
            "stall_ms_p99": pct(0.99),
            "converged": converged,
        },
    }
    try:
        with open("BENCH_fleet.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def bench_failover() -> dict:
    """Replication + failover cost (ISSUE 8): a live fleet under a
    seeded mixed-profile load (edit-heavy rooms, idle rooms, a
    reconnecting session, a session on a lossy link) loses a primary
    shard per cycle.  Measured per cycle: detection latency in ticks
    (kill -> detector conviction), the promotion wall time (WAL-assisted
    materialization of every doc the victim owned, from the
    ``ytpu_failover_seconds`` histogram), the replication lag at the
    moment of the kill, and the unavailability window.  The revived
    shard re-joins fenced, so the cycle repeats on a full-strength
    fleet.  The contract alongside the numbers: zero acknowledged-update
    loss (every room byte-identical to its uninterrupted reference) and
    no session falling back to a second full resync.

    The block is also written to BENCH_failover.json.
    """
    import tempfile

    import yjs_tpu as Y
    from yjs_tpu.fleet import FailoverConfig, FleetRouter
    from yjs_tpu.persistence import WalConfig
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
    from yjs_tpu.sync.session import SessionConfig
    from yjs_tpu.sync.transport import PipeNetwork

    n_shards = int(os.environ.get("YTPU_BENCH_FAILOVER_SHARDS", "4"))
    cycles = int(os.environ.get("YTPU_BENCH_FAILOVER_CYCLES", "6"))
    rounds = int(os.environ.get("YTPU_BENCH_FAILOVER_ROUNDS", "20"))
    rng = random.Random(23)
    # profile mix: who edits how often per round
    profiles = {
        "edit-0": 0.8, "edit-1": 0.8, "edit-2": 0.6,
        "idle-0": 0.05, "idle-1": 0.05,
        "reconnect": 0.4, "lossy": 0.4,
    }
    cfg = SessionConfig(
        retry_base=4, retry_cap=16, retry_max=6, retry_jitter=0.25,
        antientropy=8, heartbeat=0, liveness=0, hello_timeout=0, seed=23,
    )
    with tempfile.TemporaryDirectory(prefix="ytpu-bench-fo") as wd:
        fleet = FleetRouter(
            n_shards, 8, wal_dir=wd,
            wal_config=WalConfig(fsync="never"),
            failover_config=FailoverConfig(
                suspect_ticks=2, confirm_ticks=1, jitter_ticks=0,
            ),
        )
        peer = TpuProvider(2)
        refs = {}
        for g in profiles:
            d = Y.Doc(gc=False)
            d.client_id = 100 + len(refs)
            refs[g] = d
        # the lossy profile rides a faulted link; the reconnect profile
        # gets its transport killed and re-attached every cycle
        lossy_net = PipeNetwork(NetworkFaultInjector(NetChaosConfig(
            seed=23, drop=0.2, duplicate=0.2, delay=0.25, reorder=0.3,
        )))
        clean_net = PipeNetwork()
        tl_f, tl_p = lossy_net.pair()
        sessions = [
            fleet.session("lossy", "peer", cfg),
            peer.session("lossy", "fleet", cfg),
        ]
        sessions[0].connect(tl_f)
        sessions[1].connect(tl_p)
        tr_f, tr_p = clean_net.pair()
        sessions += [
            fleet.session("reconnect", "peer", cfg),
            peer.session("reconnect", "fleet", cfg),
        ]
        sessions[2].connect(tr_f)
        sessions[3].connect(tr_p)

        def sed(doc, text):
            sv = Y.encode_state_vector(doc)
            doc.get_text("text").insert(
                rng.randrange(len(str(doc.get_text("text"))) + 1), text
            )
            return Y.encode_state_as_update(doc, sv)

        def drive_round():
            for g, p in profiles.items():
                if rng.random() >= p:
                    continue
                u = sed(refs[g], rng.choice("abcdef "))
                if g in ("reconnect", "lossy"):
                    peer.receive_update(g, u)
                else:
                    fleet.receive_update(g, u)
            lossy_net.pump()
            clean_net.pump()
            fleet.tick()
            peer.flush()
            peer.tick_sessions()

        detection_ticks, lag_at_kill = [], []
        refolded = 0
        for _cyc in range(cycles):
            for _ in range(rounds):
                drive_round()
            # reconnect profile: drop the clean transport, re-pair
            clean_net.kill(tr_f, tr_p)
            tr_f, tr_p = clean_net.pair()
            sessions[2].attach(tr_f)
            sessions[3].attach(tr_p)
            # the kill: the busiest room's primary dies mid-traffic
            victim = fleet.owner_of("edit-0")
            if victim is None:
                continue
            repl_snap = fleet.repl.snapshot()
            lag_at_kill.append(max(
                [0, *repl_snap["lag"].values()]
            ))
            fleet.kill_shard(victim)
            ticks = 0
            while victim not in fleet._down and ticks < 64:
                drive_round()
                ticks += 1
            detection_ticks.append(ticks)
            res = fleet.revive_shard(victim)
            refolded += len(res.get("fenced", []))
            for _ in range(rounds // 2):
                drive_round()
        # settle the mesh so the convergence check is a fixpoint test
        for _ in range(200):
            lossy_net.pump()
            clean_net.pump()
            fleet.flush()
            fleet.tick_sessions()
            peer.flush()
            peer.tick_sessions()
        converged = all(
            fleet.text(g) == str(refs[g].get_text("text"))
            for g in profiles
            if g not in ("reconnect", "lossy")
        )
        mesh_converged = all(
            fleet.text(g) == peer.text(g)
            for g in ("reconnect", "lossy")
        )
        snap = fleet.metrics_snapshot()
        hist = snap.get("histograms", {})
        fo_s = hist.get("ytpu_failover_seconds", {}).get("", {})
        un_t = hist.get("ytpu_failover_unavailable_ticks", {}).get("", {})
        counters = snap.get("counters", {})
        full_resyncs = max(s.n_full_resyncs for s in sessions)

        def srt(xs):
            return sorted(xs) or [0]

        def pct(xs, p):
            s = srt(xs)
            return s[min(len(s) - 1, int(p * len(s)))]

        out = {
            "n_shards": n_shards,
            "cycles": cycles,
            "rounds_per_cycle": rounds,
            "profiles": {k: v for k, v in profiles.items()},
            "detection_ticks_p50": pct(detection_ticks, 0.50),
            "detection_ticks_p99": pct(detection_ticks, 0.99),
            "promotion_ms_p50": round(
                float(fo_s.get("p50", 0.0)) * 1000.0, 3
            ),
            "promotion_ms_p99": round(
                float(fo_s.get("p99", 0.0)) * 1000.0, 3
            ),
            "unavailable_ticks_p50": float(un_t.get("p50", 0.0)),
            "unavailable_ticks_p99": float(un_t.get("p99", 0.0)),
            "replication_lag_at_kill_max": max([0, *lag_at_kill]),
            "promotions_total": int(
                counters.get("ytpu_failover_promotions_total", {})
                .get("outcome=promoted", 0)
            ),
            "fenced_total": int(
                counters.get("ytpu_failover_fenced_total", {})
                .get("", 0)
            ),
            "revive_refolded_docs": refolded,
            "max_full_resyncs_per_session": full_resyncs,
            "converged": converged,
            "mesh_converged": mesh_converged,
        }
        fleet.close(checkpoint=False)
    try:
        with open("BENCH_failover.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def bench_overload() -> dict:
    """Admission + brownout under multi-tenant overload (ISSUE 10): a
    replicated fleet takes a seeded mixed-profile population (editors,
    idlers, a reconnector, a lossy link, direct abusive writers) offered
    at >= 2x its sustained admission capacity.  The brownout controller
    is expected to climb (shed-background -> coalesce -> reject-writes),
    shed the surplus via the weighted-fair queue and typed rejections,
    and return to normal within a bounded number of ticks once the load
    stops.  The contract alongside the numbers: zero acked-update loss
    (every room byte-identical between the client replica and the
    fleet), the interactive SLO never pages while background traffic
    sheds, and no session needs more than its one initial full resync.

    The block is also written to BENCH_overload.json.
    """
    import tempfile

    from yjs_tpu.admission import AdmissionConfig
    from yjs_tpu.fleet import FleetRouter
    from yjs_tpu.loadgen import LoadGen, LoadGenConfig
    from yjs_tpu.persistence import WalConfig

    n_shards = int(os.environ.get("YTPU_BENCH_OVERLOAD_SHARDS", "3"))
    n_clients = int(os.environ.get("YTPU_BENCH_OVERLOAD_CLIENTS", "12"))
    ticks = int(os.environ.get("YTPU_BENCH_OVERLOAD_TICKS", "150"))
    seed = int(os.environ.get("YTPU_BENCH_OVERLOAD_SEED", "7"))
    adm_cfg = AdmissionConfig(
        enabled=True, tenant_rate=0.5, tenant_burst=2,
        doc_rate=0.5, doc_burst=2, queue_max=16, drain_batch=4,
        up_ticks=2, down_ticks=6,
    )
    with tempfile.TemporaryDirectory(prefix="ytpu-bench-ov") as wd:
        fleet = FleetRouter(
            n_shards, 32, wal_dir=wd,
            wal_config=WalConfig(fsync="never"),
            admission_config=adm_cfg,
        )
        lg = LoadGen(fleet, LoadGenConfig(
            seed=seed, n_clients=n_clients, flush_every=8,
        ))
        t0 = time.perf_counter()
        lg.run(ticks)
        lg.drain()
        wall_s = time.perf_counter() - t0
        rep = lg.report()
        adm = rep["admission"]
        out = {
            "n_shards": n_shards,
            "n_clients": n_clients,
            "ticks": rep["ticks"],
            "seed": seed,
            "wall_s": round(wall_s, 3),
            "overload_factor": rep["overload_factor"],
            "offered_updates": adm["offered"],
            "admitted": adm["admitted"],
            "queued": adm["queued"],
            "drained": adm["drained"],
            "rejected": adm["rejected"],
            "shed_fraction": rep["shed_fraction"],
            "reject_rate": rep["reject_rate"],
            "interactive_p99_ticks": rep["interactive_p99_ticks"],
            "slo_page_ticks": rep["slo_page_ticks"],
            "max_brownout_level": rep["max_level"],
            "brownout_transitions": len(rep["transitions"]),
            "recovery_ticks": rep["recovery_ticks"],
            "convergence_failures": len(rep["convergence_failures"]),
            "max_full_resyncs_per_session": max(
                [0, *rep["session_full_resyncs"]]
            ),
        }
        fleet.close(checkpoint=False)
    try:
        with open("BENCH_overload.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def bench_tiering(n_ops: int = 200) -> dict:
    """Tiered doc-lifecycle cost (ISSUE 7), three parts:

    - **overcommit**: N engine slots serving 50xN docs under random
      demand — every touch past capacity is an auto-evict + promote
      round trip; the contract is zero ``ProviderFullError``;
    - **promotion latency**: demote→touch cycles against a WAL-backed
      provider, warm (column hydrate, no decode) vs cold (WAL read +
      decode + integrate) — p50/p99 per path plus the speedup ratio
      (acceptance: warm p99 at least 5x faster than cold replay);
    - **GC**: one forced tombstone pass over a fragmented mostly-deleted
      hot doc — rows/bytes reclaimed.

    The block is also written to BENCH_tiering.json.
    """
    import tempfile

    import yjs_tpu as Y
    from yjs_tpu.persistence import WalConfig
    from yjs_tpu.provider import ProviderFullError, TpuProvider
    from yjs_tpu.tiering import TierConfig

    tier_cfg = TierConfig(enabled=True)
    rng = random.Random(11)

    # -- overcommit churn ---------------------------------------------------
    n_slots = int(os.environ.get("YTPU_BENCH_TIER_SLOTS", "4"))
    n_docs = int(
        os.environ.get("YTPU_BENCH_TIER_DOCS", str(50 * n_slots))
    )
    n_touches = int(os.environ.get("YTPU_BENCH_TIER_TOUCHES", "300"))
    prov = TpuProvider(n_slots, tier_config=tier_cfg)
    full_errors = 0
    t0 = time.perf_counter()
    for i in range(n_docs):
        d = Y.Doc(gc=False)
        d.client_id = i + 1
        d.get_text("text").insert(0, f"room {i} payload")
        try:
            prov.receive_update(
                f"room-{i}", Y.encode_state_as_update(d)
            )
        except ProviderFullError:
            full_errors += 1
    admit_dt = time.perf_counter() - t0
    t1 = time.perf_counter()
    for _ in range(n_touches):
        g = f"room-{rng.randrange(n_docs)}"
        try:
            prov.text(g)
        except ProviderFullError:
            full_errors += 1
    touch_dt = time.perf_counter() - t1
    tier_snap = prov.tier_snapshot()
    overcommit = {
        "n_slots": n_slots,
        "n_docs": n_docs,
        "capacity_multiplier": round(n_docs / n_slots, 1),
        "provider_full_errors": full_errors,
        "admissions_per_sec": (
            round(n_docs / admit_dt, 1) if admit_dt else 0.0
        ),
        "touches": n_touches,
        "touches_per_sec": (
            round(n_touches / touch_dt, 1) if touch_dt else 0.0
        ),
        "resident": tier_snap["resident"],
        "hot": tier_snap["hot"],
        "warm": tier_snap["warm"],
        "cold": tier_snap["cold"],
    }

    # -- promotion latency: warm hydrate vs cold replay ---------------------
    # timed at the doc_id seam (the promotion itself): warm scatters the
    # detached columns back into the slot, cold re-decodes and
    # re-integrates the journaled state (flush included — that is the
    # cost warm promotion exists to skip).  Full-size traces: on a tiny
    # doc both paths drown in the device round-trip.
    reps = int(os.environ.get("YTPU_BENCH_TIER_REPS", "60"))
    promote_ops = int(
        os.environ.get("YTPU_BENCH_TIER_PROMOTE_OPS", "1500")
    )
    update = load_distinct_traces(1, promote_ops)[0]

    def pct(samples, p):
        s = sorted(samples)
        return round(s[min(len(s) - 1, int(p * len(s)))], 3)

    with tempfile.TemporaryDirectory(prefix="ytpu-bench-tier") as wd:
        # fsync="never" isolates the promotion compute path: both tiers
        # journal identically, and periodic interval-fsyncs would spike
        # the p99 of whichever path they happen to land in
        p2 = TpuProvider(
            2, wal_dir=wd, wal_config=WalConfig(fsync="never"),
            tier_config=tier_cfg,
        )
        p2.receive_update("doc", update)
        p2.flush()
        warm_ms, cold_ms = [], []
        for tier, sink in (("warm", warm_ms), ("cold", cold_ms)):
            p2.demote_doc("doc", tier)  # warm the path untimed
            p2.text("doc")
            for _ in range(reps):
                p2.demote_doc("doc", tier)
                m0 = time.perf_counter()
                p2.doc_id("doc")  # first touch = promote
                sink.append((time.perf_counter() - m0) * 1000.0)
        p2.close(checkpoint=False)
    speedup = (
        round(pct(cold_ms, 0.99) / max(1e-9, pct(warm_ms, 0.99)), 2)
    )
    promotion = {
        "reps": reps,
        "trace_ops": promote_ops,
        "warm_ms_p50": pct(warm_ms, 0.50),
        "warm_ms_p99": pct(warm_ms, 0.99),
        "cold_ms_p50": pct(cold_ms, 0.50),
        "cold_ms_p99": pct(cold_ms, 0.99),
        "warm_vs_cold_p99_speedup": speedup,
    }

    # -- forced tombstone GC ------------------------------------------------
    p3 = TpuProvider(
        1,
        tier_config=TierConfig(
            enabled=True, gc_min_rows=32, gc_deleted_ratio=0.25
        ),
    )
    d = Y.Doc(gc=False)
    d.client_id = 5
    t = d.get_text("text")
    for k in range(128):  # fragmented same-client runs
        sv = Y.encode_state_vector(d)
        t.insert(len(t.to_string()), f"frag {k} ")
        p3.receive_update("gc-doc", Y.encode_state_as_update(d, sv))
        p3.flush()
    sv = Y.encode_state_vector(d)
    t.delete(0, len(t.to_string()) - 8)
    p3.receive_update("gc-doc", Y.encode_state_as_update(d, sv))
    p3.flush()
    gc_stats = p3.tiers.gc_pass()
    converged = p3.text("gc-doc") == t.to_string()

    out = {
        "overcommit": overcommit,
        "promotion": promotion,
        "gc": {
            "docs": gc_stats["docs"],
            "rows_reclaimed": gc_stats["rows_reclaimed"],
            "bytes_reclaimed": gc_stats["bytes_reclaimed"],
        },
        "converged": converged,
    }
    try:
        with open("BENCH_tiering.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def bench_cluster() -> dict:
    """Process-native cluster cost (ISSUE 14): the SAME y-websocket
    gateway runs over real OS-process shards (Supervisor + RPC) and
    over the in-process fleet (LocalCluster), and two raw-session
    clients in one room measure end-to-end convergence per edit —
    insert on A until visible on B — so the p50/p99 delta IS the
    process-fabric tax (socket hops + serialization + per-shard
    GIL isolation).  Then the process run's owner shard takes a
    ``kill -9`` and the block reports the unavailability window: the
    supervisor's detected outage (``unavailable_s`` on the recovery
    event) and the wall-clock until both peers reconverge with the
    outage edit, plus the restart/resolution counters federated from
    the snapshot directory the monitor dropped (the same files
    ``ytpu_top --cluster`` tails).

    The block is also written to BENCH_cluster.json.
    """
    import signal
    import socket as socketlib
    import tempfile

    import yjs_tpu as Y
    from yjs_tpu.cluster import (
        ClusterConfig, Gateway, GatewayConfig, LocalCluster, Supervisor,
    )
    from yjs_tpu.cluster.rpc import RpcError
    from yjs_tpu.fleet import FleetRouter
    from yjs_tpu.obs.federate import federate_snapshots, read_snapshot_dir

    sys.path.insert(
        0, str(Path(__file__).resolve().parent / "examples")
    )
    from socket_connector import SocketConnector

    n_shards = int(os.environ.get("YTPU_BENCH_CLUSTER_SHARDS", "3"))
    n_edits = int(os.environ.get("YTPU_BENCH_CLUSTER_EDITS", "30"))
    room = "bench-room"

    def pct(samples, p):
        s = sorted(samples)
        return round(s[min(len(s) - 1, int(p * len(s)))], 2)

    def connect(port, client_id):
        doc = Y.Doc(gc=False)
        doc.client_id = client_id
        sock = socketlib.create_connection(("127.0.0.1", port), timeout=30)
        conn = SocketConnector(doc, sock, room=room, peer=f"p{client_id}")
        conn.connect()
        return doc, conn

    def edit_until_visible(a, b, token, deadline_s=60.0):
        """Insert ``token`` on A; wall ms until B's replica shows it."""
        doc_a, conn_a = a
        doc_b, conn_b = b
        t0 = time.perf_counter()
        with conn_a.lock:
            doc_a.get_text("text").insert(0, token)
        deadline = t0 + deadline_s
        while time.perf_counter() < deadline:
            with conn_b.lock:
                if token in doc_b.get_text("text").to_string():
                    return (time.perf_counter() - t0) * 1000.0
            time.sleep(0.002)
        raise TimeoutError(f"{token} never converged")

    def run_fabric(kind, wd):
        snap_dir = os.path.join(wd, "snap")
        if kind == "process":
            cluster = Supervisor(
                n_shards, os.path.join(wd, "wal"), docs_per_shard=8,
                config=ClusterConfig(
                    heartbeat_s=0.15, restart_backoff_s=0.05,
                    busy_retry_ticks=4, restart_max=2,
                    snapshot_dir=snap_dir, snapshot_s=0.5,
                ),
            ).start()
        else:
            cluster = LocalCluster(FleetRouter(
                n_shards=n_shards, docs_per_shard=8, backend="cpu",
                wal_dir=os.path.join(wd, "wal"),
            ))
        gw = Gateway(cluster, config=GatewayConfig(port=0)).start()
        out = {"kind": kind}
        pairs = []
        try:
            t0 = time.perf_counter()
            a = connect(gw.port, 1)
            b = connect(gw.port, 2)
            pairs = [a, b]
            edit_until_visible(a, b, "[warm]")  # handshake + first flush
            out["connect_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 1
            )
            lat = [
                edit_until_visible(a, b, f"[e{i}]")
                for i in range(n_edits)
            ]
            out["edits"] = n_edits
            out["converge_ms_p50"] = pct(lat, 0.50)
            out["converge_ms_p99"] = pct(lat, 0.99)

            if kind == "process":
                owner = cluster.owner_of(room)
                pid = cluster._shards[owner].pid
                k0 = time.perf_counter()
                os.kill(pid, signal.SIGKILL)
                # the outage edit: BUSY-held in the session outbox
                # until the restarted shard serves again
                reconverge_ms = edit_until_visible(
                    a, b, "[outage]", deadline_s=120.0
                )
                report = cluster.recovery_report()
                deadline = time.time() + 60
                while not report["events"] and time.time() < deadline:
                    time.sleep(0.1)
                    report = cluster.recovery_report()
                ev = report["events"][0] if report["events"] else {}
                resyncs = []
                for doc, conn in pairs:
                    with conn.lock:
                        resyncs.append(
                            conn.session.snapshot()["full_resyncs"]
                        )
                out["kill9"] = {
                    "outcome": ev.get("outcome"),
                    "unavailable_s": round(
                        float(ev.get("unavailable_s") or 0.0), 3
                    ),
                    "reconverge_s": round(reconverge_ms / 1000.0, 3),
                    "kill_to_visible_s": round(
                        time.perf_counter() - k0, 3
                    ),
                    "full_resyncs_max": max(resyncs),
                }
                # the monitor's periodic file drop, federated exactly
                # the way ytpu_top --cluster consumes it
                deadline = time.time() + 15
                while time.time() < deadline and not os.path.exists(
                    os.path.join(snap_dir, "cluster.json")
                ):
                    time.sleep(0.1)
                sources = [
                    s for s in read_snapshot_dir(snap_dir)
                    if s["label"] != "cluster"
                ]
                fed = federate_snapshots(sources)
                try:
                    with open(
                        os.path.join(snap_dir, "cluster.json")
                    ) as f:
                        dropped = json.load(f)
                except (OSError, ValueError):
                    dropped = {}
                out["federated"] = {
                    "sources": fed["federation"]["sources"],
                    "wal_records_appended_total": round(sum(
                        fed["counters"]
                        .get("ytpu_wal_records_appended_total", {})
                        .values()
                    )),
                    "report_outcomes": dropped.get("outcomes", {}),
                    "report_epoch": dropped.get("epoch"),
                }
        finally:
            for doc, conn in pairs:
                try:
                    conn.close()
                except (OSError, RpcError):
                    pass
            gw.close()
            cluster.close()
        return out

    with tempfile.TemporaryDirectory(prefix="ytpu-bench-clu") as wd_p:
        process = run_fabric("process", wd_p)
    with tempfile.TemporaryDirectory(prefix="ytpu-bench-clu") as wd_l:
        inprocess = run_fabric("inprocess", wd_l)

    out = {
        "n_shards": n_shards,
        "process": process,
        "inprocess": inprocess,
        "process_tax_p50": (
            round(
                process["converge_ms_p50"]
                / max(1e-9, inprocess["converge_ms_p50"]),
                2,
            )
        ),
    }
    try:
        with open("BENCH_cluster.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def bench_geo() -> dict:
    """Cross-region convergence under injected WAN latency (ISSUE 17):
    three regions in a full GeoReplicator mesh, each link delayed by a
    seeded RTT distribution, and per-update convergence measured as
    ticks from accepted-at-origin until visible in EVERY region.  The
    whole mesh is tick-driven, so the numbers are deterministic —
    latency comes from the injected delay plus the delta scheduler's
    own batching, never from the host machine.

    Reported per injected RTT {50, 150, 300} ms: convergence p50/p99
    in ms, plus ``p99_over_floor`` — the p99 as a multiple of the
    one-way propagation floor (rtt/2; the acceptance band is <= 5x at
    150 ms).  A final leg severs one link at 150 ms RTT mid-edit and
    reports the partition-heal catch-up time.

    The block is also written to BENCH_geo.json.
    """
    import yjs_tpu as Y
    from yjs_tpu.geo import GeoConfig, GeoReplicator
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.resilience import NetChaosConfig, NetworkFaultInjector
    from yjs_tpu.sync.session import SessionConfig
    from yjs_tpu.sync.transport import PipeNetwork

    tick_ms = int(os.environ.get("YTPU_BENCH_GEO_TICK_MS", "5"))
    n_edits = int(os.environ.get("YTPU_BENCH_GEO_EDITS", "40"))
    regions = ("A", "B", "C")
    rooms = ("room-0", "room-1", "room-2")
    session_cfg = SessionConfig(
        seed=7, heartbeat=0, liveness=0, antientropy=8,
        hello_timeout=0, retry_base=4, retry_cap=16, retry_max=6,
    )

    def mk_update(token, client_id):
        d = Y.Doc(gc=False)
        d.client_id = client_id
        d.get_text("text").insert(0, token)
        return Y.encode_state_as_update(d)

    def mk_mesh(rtt_ms, faults_off=False):
        one_way_ticks = max(1, rtt_ms // 2 // tick_ms)
        provs = {r: TpuProvider(8, backend="cpu") for r in regions}
        reps = {
            r: GeoReplicator(
                provs[r],
                GeoConfig(region=r, seed=11 + i, tick_ms=tick_ms),
            )
            for i, r in enumerate(regions)
        }
        nets = {}
        for i, (x, y) in enumerate((("A", "B"), ("A", "C"), ("B", "C"))):
            inj = None
            if not faults_off:
                inj = NetworkFaultInjector(NetChaosConfig(
                    seed=97 + i, rtt_ticks=one_way_ticks,
                    rtt_jitter_ticks=max(1, one_way_ticks // 4),
                ))
            net = PipeNetwork(inj)
            nets[(x, y)] = net
            tx, ty = net.pair(f"geo:{x}", f"geo:{y}")
            reps[x].add_peer(y, (lambda t: (lambda: t))(tx),
                             session_config=session_cfg)
            reps[y].add_peer(x, (lambda t: (lambda: t))(ty),
                             session_config=session_cfg)
        return provs, reps, nets

    def step(provs, reps, nets):
        for p in provs.values():
            p.flush()
        for rep in reps.values():
            rep.tick()
        for net in nets.values():
            net.pump()

    def visible_everywhere(provs, room, token):
        return all(
            room in p.guids() and token in p.text(room)
            for p in provs.values()
        )

    def pct(samples, p):
        s = sorted(samples)
        return s[min(len(s) - 1, int(p * len(s)))]

    def run_rtt(rtt_ms):
        provs, reps, nets = mk_mesh(rtt_ms)
        for _ in range(60):  # handshakes settle
            step(provs, reps, nets)
        lat_ticks = []
        for n in range(n_edits):
            origin = regions[n % len(regions)]
            room = rooms[n % len(rooms)]
            token = f"[{origin}{n}]"
            provs[origin].receive_update(
                room, mk_update(token, 1000 + n)
            )
            ticks = 0
            while not visible_everywhere(provs, room, token):
                step(provs, reps, nets)
                ticks += 1
                if ticks > 4000:
                    raise RuntimeError(f"{token} never converged")
            lat_ticks.append(ticks)
        floor_ms = max(1, rtt_ms // 2)
        p50 = pct(lat_ticks, 0.50) * tick_ms
        p99 = pct(lat_ticks, 0.99) * tick_ms
        return {
            "rtt_ms": rtt_ms,
            "one_way_ticks": max(1, rtt_ms // 2 // tick_ms),
            "n_updates": len(lat_ticks),
            "p50_ms": p50,
            "p99_ms": p99,
            "floor_ms": floor_ms,
            "p50_over_floor": round(p50 / floor_ms, 2),
            "p99_over_floor": round(p99 / floor_ms, 2),
        }

    def run_heal(rtt_ms):
        """Sever A<->B mid-edit, keep editing through the outage, then
        restore the link and count ticks until full convergence."""
        provs, reps, nets = mk_mesh(rtt_ms)
        for _ in range(60):
            step(provs, reps, nets)
        net_ab = nets[("A", "B")]
        good_inj = net_ab.injector
        net_ab.injector = NetworkFaultInjector(
            NetChaosConfig(seed=5, drop=1.0)
        )
        outage_ticks = 120
        for n in range(outage_ticks):
            if n % 4 == 0:
                origin = regions[n % len(regions)]
                provs[origin].receive_update(
                    f"room-{n % 3}", mk_update(f"[o{n}]", 5000 + n)
                )
            step(provs, reps, nets)
        net_ab.injector = good_inj
        ticks = 0
        while True:
            done = all(
                provs["A"].text(room) == provs["B"].text(room)
                == provs["C"].text(room)
                for room in rooms
                if any(room in p.guids() for p in provs.values())
            )
            if done:
                break
            step(provs, reps, nets)
            ticks += 1
            if ticks > 6000:
                raise RuntimeError("mesh never healed")
        return {
            "rtt_ms": rtt_ms,
            "outage_ms": outage_ticks * tick_ms,
            "catchup_ms": ticks * tick_ms,
        }

    out = {
        "tick_ms": tick_ms,
        "n_edits": n_edits,
    }
    for rtt_ms in (50, 150, 300):
        out[f"rtt_ms_{rtt_ms}"] = run_rtt(rtt_ms)
    out["heal"] = run_heal(150)
    try:
        with open("BENCH_geo.json", "w") as f:
            json.dump(out, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    return out


def main():
    n_docs_b4 = int(os.environ.get("YTPU_BENCH_DOCS", "16384"))
    # 1024 when the pre-generated fixture exists (the r2-verdict shape);
    # synthesis-bound 64 otherwise
    _fixture = (
        Path(__file__).resolve().parent
        / "tests" / "fixtures"
        / f"distinct_traces_{os.environ.get('YTPU_BENCH_OPS', '1500')}.bin"
    )
    _have_fixture = _fixture.exists() or _fixture.with_suffix(".bin.z").exists()
    n_docs_distinct = int(
        os.environ.get(
            "YTPU_BENCH_DISTINCT_DOCS", "1024" if _have_fixture else "64"
        )
    )
    n_ops = int(os.environ.get("YTPU_BENCH_OPS", "1500"))

    # the HEADLINE is the distinct-doc engine path: per-doc decode, plan,
    # pack, transfer, apply — what a production server does per room
    # (VERDICT r4 item 2: lead with the honest number; the broadcast
    # fan-out shape stays in detail as the amortized best case)
    distinct, eng = bench_distinct(n_docs_distinct, n_ops)
    # let the timed loop's freed engines finish their device-side buffer
    # deletes before timing sync (cleanup RPCs share the host core)
    time.sleep(3)
    sync = bench_sync(eng, n_docs_distinct)
    # capture the headline engine's obs state (snapshot + Chrome trace)
    # before it dies — the artifacts prove what the timed runs did
    obs_summary = write_obs_artifacts(eng)
    del eng
    import gc

    gc.collect()
    time.sleep(3)
    storm, storm_eng = bench_distinct(
        int(os.environ.get("YTPU_BENCH_STORM_DOCS", "256")),
        n_ops, kind="storm", runs=1,
    )
    del storm_eng
    gc.collect()
    time.sleep(3)
    frag = bench_fragmented(
        int(os.environ.get("YTPU_BENCH_FRAG_DOCS", "64")),
        int(os.environ.get("YTPU_BENCH_FRAG_CHARS", "100000")),
    )
    time.sleep(3)
    planner = bench_planner()
    time.sleep(3)
    flush = bench_flush()
    time.sleep(3)
    b4 = bench_b4_broadcast(n_docs_b4)
    time.sleep(3)
    resilience = bench_resilience()
    time.sleep(3)
    durability = bench_durability()
    time.sleep(3)
    network = bench_network()
    time.sleep(3)
    fleet = bench_fleet()
    time.sleep(3)
    tiering = bench_tiering()
    time.sleep(3)
    failover = bench_failover()
    time.sleep(3)
    overload = bench_overload()
    time.sleep(3)
    cluster = bench_cluster()
    time.sleep(3)
    geo = bench_geo()
    time.sleep(3)
    obs_prof = bench_obs_prof()
    try:
        prefix = os.environ.get("YTPU_BENCH_OBS_PREFIX", "BENCH_obs")
        with open(f"{prefix}_prof.json", "w") as f:
            json.dump(obs_prof, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    time.sleep(3)
    obs_dist = bench_obs_dist()
    try:
        prefix = os.environ.get("YTPU_BENCH_OBS_PREFIX", "BENCH_obs")
        with open(f"{prefix}_dist.json", "w") as f:
            json.dump(obs_dist, f, indent=2)
    except OSError:
        pass  # artifact only; the inline detail block is authoritative
    time.sleep(3)
    obs_admin = bench_obs_admin()
    time.sleep(3)
    obs_tsdb = bench_obs_tsdb()
    time.sleep(3)
    capacity = bench_capacity()
    sweep = (
        sweep_distinct(n_ops)
        if os.environ.get("YTPU_BENCH_SWEEP")
        else None
    )

    node_proxy_distinct = distinct["cpu_py_elems_per_sec"] * NODE_PROXY_FACTOR
    node_proxy_b4 = b4["cpu_py_elems_per_sec"] * NODE_PROXY_FACTOR
    headline = distinct["e2e_elems_per_sec"]
    uniq = distinct["unique_traces"]
    distinct_label = (
        f"{distinct['n_docs']} DISTINCT docs"
        if uniq >= distinct["n_docs"]
        else f"{distinct['n_docs']} docs cycling {uniq} unique traces"
    )
    result = {
        "metric": "distinct_docs_e2e_elements_per_sec",
        "value": headline,
        "unit": (
            f"elem/s end-to-end ({distinct_label} x "
            f"{n_ops}-op traces through the full engine path: decode+plan+"
            f"pack+transfer+apply; vs Node PROXY = python_core x"
            f"{NODE_PROXY_FACTOR:g}, see BASELINE.md.  Broadcast fan-out "
            f"case in detail.b4_broadcast)"
        ),
        "vs_baseline": (
            round(headline / node_proxy_distinct, 2)
            if node_proxy_distinct
            else 0
        ),
        "detail": {
            "distinct_engine_path": distinct,
            "conflict_storm_4client": storm,
            "prepend_fragmented": frag,
            "planner": planner,
            "flush": flush,
            "sync_step2_batched": sync,
            "b4_broadcast": b4,
            "node_proxy_factor": NODE_PROXY_FACTOR,
            "node_proxy_distinct_elems_per_sec": round(node_proxy_distinct, 1),
            "node_proxy_b4_elems_per_sec": round(node_proxy_b4, 1),
            "b4_broadcast_vs_proxy": (
                round(b4["e2e_elems_per_sec"] / node_proxy_b4, 2)
                if node_proxy_b4
                else 0
            ),
            "distinct_e2e_vs_python": round(
                distinct["e2e_elems_per_sec"]
                / max(1.0, distinct["cpu_py_elems_per_sec"]),
                2,
            ),
            "obs": obs_summary,
            "obs_prof": obs_prof,
            "obs_dist": obs_dist,
            "obs_admin": obs_admin,
            "obs_tsdb": obs_tsdb,
            "capacity": capacity,
            "resilience": resilience,
            "durability": durability,
            "network": network,
            "fleet": fleet,
            "tiering": tiering,
            "failover": failover,
            "overload": overload,
            "cluster": cluster,
            "geo": geo,
        },
    }
    if sweep is not None:
        result["detail"]["distinct_scaling_sweep"] = sweep
    print(json.dumps(result))


if __name__ == "__main__":
    main()
