"""Benchmark: batched device applyUpdate vs the single-threaded CPU core.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload: a synthetic B4-style two-client editing trace (interleaved typing
bursts, deletes, periodic sync — modelled on the real-world trace statistics
cited in reference INTERNALS.md:128-130), replayed independently by B docs.
The host transcodes the merged update once and broadcasts the plan across the
batch (every doc receives the same bytes, as in the BASELINE.json "100k-doc
B4-trace replay" config); the device integrates all B docs in one vmapped
kernel call.

value = device-integrated CRDT elements/second (elements = characters +
tombstoned chars, identical work for both paths).  vs_baseline = that rate
over the single-threaded CPU reference core's applyUpdate rate on the same
update (the in-repo stand-in for the reference's single-threaded JS path:
Node.js is not available in this image).

Env knobs: YTPU_BENCH_DOCS (default 4096), YTPU_BENCH_OPS (default 1500).
"""

from __future__ import annotations

import json
import os
import random
import sys
import time

import numpy as np


def gen_trace(n_ops: int, seed: int = 7):
    """Two clients, typing bursts + deletes + periodic sync; returns the
    final merged update and the reference doc."""
    import yjs_tpu as Y

    gen = random.Random(seed)
    a = Y.Doc(gc=False)
    a.client_id = 101
    b = Y.Doc(gc=False)
    b.client_id = 202
    words = ["the ", "quick ", "brown ", "fox ", "jumps ", "over ", "lazy ", "dog . "]

    def sync():
        ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
        ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
        Y.apply_update(b, ua)
        Y.apply_update(a, ub)

    ops = 0
    while ops < n_ops:
        d = a if gen.random() < 0.5 else b
        t = d.get_text("text")
        cursor = gen.randint(0, len(t))
        burst = gen.randint(3, 12)
        for _ in range(burst):  # typing burst at a cursor
            if gen.random() < 0.8 or len(t) == 0:
                w = gen.choice(words)
                cursor = min(cursor, len(t))
                t.insert(cursor, w)
                cursor += len(w)
            else:
                pos = gen.randrange(len(t))
                n = min(gen.randint(1, 4), len(t) - pos)
                t.delete(pos, n)
                cursor = min(cursor, len(t))
            ops += 1
        if gen.random() < 0.3:
            sync()
    sync()
    assert a.get_text("text").to_string() == b.get_text("text").to_string()
    return Y.encode_state_as_update(a), a


def main():
    import jax
    import jax.numpy as jnp

    import yjs_tpu as Y
    from yjs_tpu.ops import kernels
    from yjs_tpu.ops.columns import NULL, DocMirror

    n_docs = int(os.environ.get("YTPU_BENCH_DOCS", "4096"))
    n_ops = int(os.environ.get("YTPU_BENCH_OPS", "1500"))

    update, ref_doc = gen_trace(n_ops)

    # ---- CPU baseline: single-threaded reference-core applyUpdate ----------
    t0 = time.perf_counter()
    cpu_doc = Y.Doc(gc=False)
    Y.apply_update(cpu_doc, update)
    cpu_time = time.perf_counter() - t0
    sv = Y.decode_state_vector(Y.encode_state_vector(cpu_doc))
    n_elements = sum(sv.values())
    if n_elements == 0:
        print(json.dumps({"metric": "batched_apply_update_elements_per_sec",
                          "value": 0, "unit": "elem/s (empty workload)",
                          "vs_baseline": 0}))
        return
    cpu_rate = n_elements / cpu_time

    # ---- host transcode (once) + broadcast across the doc batch ------------
    mirror = DocMirror("text")
    mirror.ingest(update, v2=False)
    t0 = time.perf_counter()
    plan = mirror.prepare_step()
    transcode_time = time.perf_counter() - t0
    n = mirror.n_rows
    # the level kernel scatters masked lanes into >= 2W spare slots past n
    packed = plan.packed_levels()
    w_pad = max((len(lv) for lv in packed), default=1)
    cap = max(64, n + 2 * w_pad)
    cols = mirror.static_columns()

    def pad_col(key, fill, dtype):
        arr = np.full((cap + 1,), fill, dtype)
        arr[:n] = cols[key]
        return np.broadcast_to(arr, (n_docs, cap + 1))

    statics = {
        "client_key": pad_col("client_key", 0, np.uint32),
        "origin_slot": pad_col("origin_slot", NULL, np.int32),
        "origin_clock": pad_col("origin_clock", 0, np.int32),
        "right_slot": pad_col("right_slot", NULL, np.int32),
        "right_clock": pad_col("right_clock", 0, np.int32),
        "origin_row": pad_col("origin_row", NULL, np.int32),
    }
    sched = np.full((n_docs, 1, 4), NULL, np.int32)
    lv_sched = np.full((n_docs, 1, 1, 6), NULL, np.int32)
    if plan.sched:
        sched = np.broadcast_to(
            np.asarray(plan.sched, np.int32), (n_docs, len(plan.sched), 4)
        )
        one = np.full((len(packed), w_pad, 6), NULL, np.int32)
        for lv, entries in enumerate(packed):
            if entries:
                one[lv, : len(entries)] = entries
        lv_sched = np.broadcast_to(one, (n_docs,) + one.shape)
    splits = np.full((n_docs, 1, 2), NULL, np.int32)
    if plan.splits:
        splits = np.broadcast_to(
            np.asarray(plan.splits, np.int32), (n_docs, len(plan.splits), 2)
        )
    dels = np.full((n_docs, 1), NULL, np.int32)
    if plan.delete_rows:
        dels = np.broadcast_to(
            np.asarray(plan.delete_rows, np.int32), (n_docs, len(plan.delete_rows))
        )

    seg_cap = max(8, mirror.n_segs)

    def fresh_dyn():
        return (
            jnp.full((n_docs, cap + 1), NULL, jnp.int32),
            jnp.zeros((n_docs, cap + 1), bool),
            jnp.full((n_docs, seg_cap + 1), NULL, jnp.int32),
        )

    statics_d = {k: jnp.asarray(v) for k, v in statics.items()}
    splits_d, sched_d, dels_d = jnp.asarray(splits), jnp.asarray(sched), jnp.asarray(dels)
    lv_d = jnp.asarray(lv_sched)
    scratch_base = jnp.full((n_docs,), n, jnp.int32)

    if os.environ.get("YTPU_KERNEL") == "seq":
        step = lambda dyn: kernels.batch_step(statics_d, dyn, splits_d, sched_d, dels_d)
    else:
        step = lambda dyn: kernels.batch_step_levels(
            statics_d, dyn, splits_d, lv_d, dels_d, scratch_base
        )

    # warmup/compile (block_until_ready does not synchronize on the axon
    # tunnel backend — force completion with a device->host readback)
    out = step(fresh_dyn())
    np.asarray(out[2])

    # timed: K chained dispatches, one readback (amortizes the ~90ms tunnel
    # round-trip out of the per-step figure)
    K = 8
    t0 = time.perf_counter()
    for _ in range(K):
        out = step(fresh_dyn())
    np.asarray(out[0][:, 0])  # readback forces full completion
    device_time = (time.perf_counter() - t0) / K
    device_rate = n_docs * n_elements / device_time

    # correctness spot-check: doc 0's visible text vs the CPU core
    from yjs_tpu.ops.engine import visible_text

    right, deleted, start = out
    text_seg = mirror.segments[("text", None)]
    valid = np.zeros(cap + 1, bool)
    valid[:n] = np.asarray(mirror.row_seg, np.int32) == text_seg
    d = np.asarray(kernels.list_ranks(right[:1], jnp.asarray(valid)[None]))[0]
    dels_out = np.asarray(deleted[0])
    rows = np.nonzero(d >= 0)[0]
    rows = rows[np.argsort(-d[rows], kind="stable")]
    text = visible_text(mirror, rows, dels_out[rows])
    expect = cpu_doc.get_text("text").to_string()
    if text != expect:
        print(json.dumps({"metric": "FAILED_convergence_check", "value": 0,
                          "unit": "", "vs_baseline": 0}))
        sys.exit(1)

    result = {
        "metric": "batched_apply_update_elements_per_sec",
        "value": round(device_rate, 1),
        "unit": f"elem/s ({n_docs} docs x {n_elements} elems; host transcode "
                f"{transcode_time*1e3:.0f}ms excluded; cpu ref {cpu_rate:,.0f}/s)",
        "vs_baseline": round(device_rate / cpu_rate, 2),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
