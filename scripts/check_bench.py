#!/usr/bin/env python
"""check_bench: the bench-regression gate (ISSUE 16).

Re-runs the headline bench blocks in a scratch directory, then diffs
their fresh numbers against the committed ``BENCH_*.json`` baselines
with a per-metric tolerance band — exit 1 on any regression, so a perf
cliff fails CI the same way a broken test does.

Guarded metrics (direction-aware: a *better* number never fails):

    BENCH_planner.json   cold_vs_warm_ratio      lower is better
    BENCH_flush.json     overlap_fraction        higher is better
    BENCH_cluster.json   process.converge_ms_p50 lower is better
    BENCH_overload.json  shed_fraction           higher is better
    BENCH_geo.json       rtt_ms_150.p99_over_floor  lower is better
    BENCH_geo.json       heal.catchup_ms         lower is better
    BENCH_capacity.json  sessions_per_device     higher is better

Modes:

    python scripts/check_bench.py
        Run the four bench blocks fresh (minutes; spawns the process
        cluster) and compare.  The opt-in ``YTPU_CI_BENCH=1`` stage of
        ``scripts/ci_check.sh``.

    python scripts/check_bench.py --fresh-dir DIR
        Skip the benchmarks and compare DIR's ``BENCH_*.json`` files
        against the baselines — for unit tests of the comparison
        logic, or for gating numbers produced on another machine.

    python scripts/check_bench.py --list
        Print the guarded metrics, baselines, and bands; exit 0.

``--baseline-dir`` points somewhere other than the repo root;
``--tolerance NAME=FLOAT`` (repeatable) overrides one band, e.g.
``--tolerance planner.cold_vs_warm_ratio=0.5``.

Tolerances are wide on purpose: CI containers are noisy neighbors and
this gate exists to catch cliffs (a 2x planner regression, an overlap
collapse), not 5% jitter.  Committed baselines only move when a PR
deliberately reruns ``python bench.py`` and commits the artifacts.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# (name, artifact file, key path, direction, relative tolerance).
# direction "lower": fresh may not exceed baseline*(1+tol);
# direction "higher": fresh may not fall below baseline*(1-tol).
METRICS = (
    ("planner.cold_vs_warm_ratio", "BENCH_planner.json",
     ("cold_vs_warm_ratio",), "lower", 0.40),
    ("flush.overlap_fraction", "BENCH_flush.json",
     ("overlap_fraction",), "higher", 0.20),
    ("cluster.converge_ms_p50", "BENCH_cluster.json",
     ("process", "converge_ms_p50"), "lower", 1.00),
    ("overload.shed_fraction", "BENCH_overload.json",
     ("shed_fraction",), "higher", 0.10),
    # the geo mesh is fully tick-driven, so these are deterministic on
    # a given seed — the band only absorbs scheduler-tweak drift, not
    # host noise (ISSUE 17 acceptance: p99 <= 5x the RTT floor)
    ("geo.converge_p99_x_floor", "BENCH_geo.json",
     ("rtt_ms_150", "p99_over_floor"), "lower", 1.00),
    ("geo.heal_catchup_ms", "BENCH_geo.json",
     ("heal", "catchup_ms"), "lower", 1.00),
    # sessions-per-device at interactive SLO (ISSUE 19): the published
    # capacity figure, knee read from TSDB history.  Wall-clock-SLO
    # bound, so the band is the widest — the gate catches a halving,
    # not scheduler jitter
    ("capacity.sessions_per_device", "BENCH_capacity.json",
     ("sessions_per_device",), "higher", 0.50),
    # telemetry overhead (ISSUE 19 pin: < 1% of flush-loop wall).
    # overhead_pct is instrumented at the obs seams (hook + sampler
    # perf_counter sums over the run wall), so it is stable on noisy
    # shared hosts where an A/B wall-clock diff is not
    ("obs_tsdb.overhead_pct", "BENCH_obs_tsdb.json",
     ("overhead_pct",), "lower", 1.00),
)


def _dig(d: dict, path: tuple) -> float | None:
    for k in path:
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    try:
        return float(d)
    except (TypeError, ValueError):
        return None


def _load(path: Path) -> dict:
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return {}
    return d if isinstance(d, dict) else {}


def compare(
    fresh_dir: Path, baseline_dir: Path, tolerances: dict[str, float]
) -> list[dict]:
    """One verdict dict per guarded metric.  A missing fresh artifact
    or key is itself a failure (the bench block silently dying must
    not read as "no regression"); a missing *baseline* is skipped with
    a note, so the gate can precede the first committed artifact."""
    verdicts = []
    for name, fname, path, direction, tol in METRICS:
        tol = tolerances.get(name, tol)
        base = _dig(_load(baseline_dir / fname), path)
        fresh = _dig(_load(fresh_dir / fname), path)
        v = {
            "metric": name, "file": fname, "direction": direction,
            "baseline": base, "fresh": fresh, "tolerance": tol,
            "status": "ok", "bound": None,
        }
        if base is None:
            v["status"] = "no-baseline"
        elif fresh is None:
            v["status"] = "missing-fresh"
        elif direction == "lower":
            v["bound"] = base * (1.0 + tol)
            if fresh > v["bound"]:
                v["status"] = "regression"
        else:
            v["bound"] = base * (1.0 - tol)
            if fresh < v["bound"]:
                v["status"] = "regression"
        verdicts.append(v)
    return verdicts


def run_benchmarks(out_dir: Path) -> None:
    """Run the guarded bench blocks with ``out_dir`` as the artifact
    cwd (bench.py writes its BENCH_*.json relative to the cwd)."""
    import bench

    cwd = os.getcwd()
    os.chdir(out_dir)
    try:
        bench.bench_planner()
        bench.bench_flush()
        bench.bench_overload()
        bench.bench_cluster()
        bench.bench_geo()
        bench.bench_capacity()
        bench.bench_obs_tsdb()
    finally:
        os.chdir(cwd)


def render(verdicts: list[dict]) -> str:
    lines = []
    for v in verdicts:
        arrow = "<=" if v["direction"] == "lower" else ">="
        bound = "-" if v["bound"] is None else f"{v['bound']:.4g}"
        lines.append(
            f"  {v['status']:>13}  {v['metric']:<28} "
            f"fresh={v['fresh'] if v['fresh'] is not None else '-':>8} "
            f"{arrow} bound={bound:>8} "
            f"(baseline={v['baseline']}, tol={v['tolerance']:.0%})"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check_bench", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--fresh-dir", default=None, metavar="DIR",
                    help="compare DIR's BENCH_*.json instead of "
                         "re-running the bench blocks")
    ap.add_argument("--baseline-dir", default=None, metavar="DIR",
                    help="committed baselines (default: repo root)")
    ap.add_argument("--tolerance", action="append", default=[],
                    metavar="NAME=FLOAT",
                    help="override one metric's band, e.g. "
                         "planner.cold_vs_warm_ratio=0.5")
    ap.add_argument("--list", action="store_true",
                    help="print the guarded metrics and exit")
    args = ap.parse_args(argv)

    baseline_dir = Path(
        args.baseline_dir
        if args.baseline_dir is not None
        else Path(__file__).resolve().parent.parent
    )
    tolerances: dict[str, float] = {}
    known = {m[0] for m in METRICS}
    for spec in args.tolerance:
        name, _, val = spec.partition("=")
        if name not in known or not val:
            ap.error(f"unknown --tolerance {spec!r} (metrics: "
                     f"{', '.join(sorted(known))})")
        tolerances[name] = float(val)

    if args.list:
        for name, fname, path, direction, tol in METRICS:
            base = _dig(_load(baseline_dir / fname), path)
            print(f"  {name:<28} {fname:<22} {direction:<7} "
                  f"tol={tolerances.get(name, tol):.0%} baseline={base}")
        return 0

    if args.fresh_dir is not None:
        verdicts = compare(Path(args.fresh_dir), baseline_dir, tolerances)
    else:
        with tempfile.TemporaryDirectory(prefix="ytpu-bench-") as td:
            print("check_bench: running bench blocks (this takes a "
                  "few minutes)...", flush=True)
            run_benchmarks(Path(td))
            verdicts = compare(Path(td), baseline_dir, tolerances)

    print("check_bench verdicts:")
    print(render(verdicts))
    bad = [v for v in verdicts
           if v["status"] in ("regression", "missing-fresh")]
    if bad:
        print(f"check_bench: FAILED ({len(bad)} regression(s))",
              file=sys.stderr)
        return 1
    print("check_bench: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
