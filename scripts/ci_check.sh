#!/usr/bin/env bash
# Repo CI gate: the metrics/docs schema check plus the fast test tier.
# Run from anywhere; JAX_PLATFORMS defaults to cpu (override to target
# an accelerator).
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"

echo "== static analysis (ytpu-lint) =="
# the pure-ast checker suite (ISSUE 13): donation-aliasing, retrace
# hazards, lock discipline/ordering, seam completeness, knob/metric
# drift — exits nonzero on any unsuppressed finding or stale baseline
python scripts/ytpu_lint.py --ci

echo "== metrics schema =="
python scripts/check_metrics_schema.py

echo "== trace validity (check_trace selftest) =="
# builds a 3-shard replicated fleet with everything sampled and
# validates the merged Perfetto trace: all flow arrows resolve, every
# sampled chain completes origin -> visible (ISSUE 11)
python scripts/check_trace.py --selftest

if [[ "${YTPU_CI_BENCH:-0}" == "1" ]]; then
    echo "== bench-regression gate (YTPU_CI_BENCH=1) =="
    # opt-in: re-runs the headline bench blocks (minutes) and diffs
    # against the committed BENCH_*.json baselines (ISSUE 16)
    python scripts/check_bench.py
fi

echo "== telemetry history smoke (marker: tsdb) =="
# the embedded TSDB (ISSUE 19) is the newest subsystem: codec
# round-trips, downsample-tier oracles, torn-read hammers, and
# crash-truncation reload regressions surface fast and isolated
python -m pytest tests/ -q -m 'tsdb and not slow' -p no:cacheprovider

echo "== cost attribution smoke (marker: cost) =="
# the per-doc/per-tenant cost ledger + capacity model (ISSUE 19):
# attribution proportionality, top-K cardinality bounds, and the
# TSDB-derived sessions-per-device knee
python -m pytest tests/ -q -m 'cost and not slow' -p no:cacheprovider

echo "== geo replication smoke (marker: geo) =="
# the multi-region active-active suite (ISSUE 17) is the newest
# subsystem: doc-space codecs, the budgeted WAN delta scheduler,
# one-way-partition/flap chaos convergence, and journaled-floor
# resume-after-kill regressions surface fast and isolated
python -m pytest tests/ -q -m 'geo and not slow' -p no:cacheprovider

echo "== admin plane smoke (marker: admin) =="
# the per-process introspection plane (ISSUE 16): endpoint unit tests,
# readiness/fencing semantics, scrape-race hardening, and the
# concurrent-scrape hammer
python -m pytest tests/ -q -m 'admin and not slow' -p no:cacheprovider

echo "== cluster smoke (marker: cluster) =="
# the process-native cluster suite (ISSUE 14) is the newest subsystem:
# real OS-process shards behind the y-websocket gateway — kill -9
# recovery, replica failover, wire-compat, launcher, and supervision
# panel regressions surface fast and isolated
python -m pytest tests/ -q -m 'cluster and not slow' -p no:cacheprovider

echo "== analysis smoke (marker: analysis) =="
# the ytpu-lint framework suite (ISSUE 13): fixture corpus, suppression
# and baseline round-trips, and the whole-repo self-run
python -m pytest tests/ -q -m 'analysis and not slow' -p no:cacheprovider

echo "== flush pipeline smoke (marker: flushpipe) =="
# the pipelined-flush + donation + adaptive-tick suite (ISSUE 12) is
# the newest subsystem: pipeline-on/off byte-identity, donation
# aliasing, and tick-controller regressions surface fast and isolated
python -m pytest tests/ -q -m 'flushpipe and not slow' -p no:cacheprovider

echo "== tracing smoke (marker: tracing) =="
# the causal-tracing + flight-recorder + federation suite (ISSUE 11)
# is the newest subsystem: context-propagation, envelope-compat, and
# merge-semantics regressions surface fast and isolated
python -m pytest tests/ -q -m 'tracing and not slow' -p no:cacheprovider

echo "== admission smoke (marker: admission) =="
# the rate-limit + brownout suite (ISSUE 10) is the newest subsystem:
# bucket/fair-queue, hysteresis, and BUSY-backpressure regressions
# surface fast and isolated
python -m pytest tests/ -q -m 'admission and not slow' -p no:cacheprovider

echo "== overload harness smoke (marker: loadgen) =="
# the seeded multi-tenant overload harness (ISSUE 10): acked-loss /
# convergence / SLO-protection invariants under >2x offered load
python -m pytest tests/ -q -m 'loadgen and not slow' -p no:cacheprovider

echo "== planner smoke (marker: planner) =="
# the plan-cache + segment-planning suite (ISSUE 9/15) is the newest
# subsystem: cache-aliasing and fast-path-divergence regressions
# surface fast and isolated
python -m pytest tests/ -q -m 'planner and not slow' -p no:cacheprovider

echo "== planner oracle corpus under np and jax backends (ISSUE 15) =="
# the device-authoritative cold planner defaults to the fused "device"
# lane; rerun the seeded oracle corpus with each fallback backend pinned
# so a kernels-only or numpy-only regression can't hide behind the
# default — the corpus asserts device-planned ranks == sequential YATA
# walk ranks struct-for-struct, byte-identical states included
YTPU_PLAN_SEGMENT=np python -m pytest tests/test_segment_planner.py -q \
    -m 'not slow' -p no:cacheprovider
YTPU_PLAN_SEGMENT=jax python -m pytest tests/test_segment_planner.py -q \
    -m 'not slow' -p no:cacheprovider

echo "== failover smoke (marker: failover) =="
# the replication + failure-detection suite (ISSUE 8) is the newest
# subsystem: fan-out, detector, promotion, and fencing regressions
# surface fast and isolated
python -m pytest tests/ -q -m 'failover and not slow' -p no:cacheprovider

echo "== tiering smoke (marker: tiering) =="
# the doc-lifecycle suite (ISSUE 7) is the newest subsystem: demotion /
# promotion / recovery-placement regressions surface fast and isolated
python -m pytest tests/ -q -m 'tiering and not slow' -p no:cacheprovider

echo "== fleet smoke (marker: fleet) =="
# the sharded-fleet suite (ISSUE 6) runs first as a fast standalone
# smoke: routing, migration, and recovery regressions surface before
# the full tier sinks time into everything else
python -m pytest tests/ -q -m 'fleet and not slow' -p no:cacheprovider

echo "== tier-1 tests (not slow) =="
# includes the chaos / durability / network / fleet marker suites (all
# deterministic); deselect one with e.g. -m 'not slow and not network'
python -m pytest tests/ -q -m 'not slow' -p no:cacheprovider
