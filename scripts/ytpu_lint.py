#!/usr/bin/env python
"""ytpu-lint — project-specific static analysis for the y-tpu codebase.

Runs the :mod:`yjs_tpu.analysis` checker suite (donation-aliasing,
retrace-hazard, lock-discipline/-ordering, seam-completeness, knob/
metric drift) over ``yjs_tpu/`` and ``scripts/`` and reports findings
not covered by an inline ``# ytpu-lint: disable…`` suppression or the
committed baseline (``.ytpu-lint-baseline.json``).

    python scripts/ytpu_lint.py                # human-readable report
    python scripts/ytpu_lint.py --ci           # exit 1 on any finding
    python scripts/ytpu_lint.py --json         # machine-readable dump
    python scripts/ytpu_lint.py --list-rules   # rule id -> severity
    python scripts/ytpu_lint.py --write-baseline   # grandfather current

Exit codes: 0 clean (or findings in non-CI mode with only advice), 1
findings/stale baseline entries present, 2 usage error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from yjs_tpu.analysis import (  # noqa: E402
    Baseline,
    all_rules,
    render_report,
    run_lint,
)

DEFAULT_BASELINE = ROOT / ".ytpu-lint-baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ytpu_lint", description=__doc__)
    ap.add_argument(
        "targets",
        nargs="*",
        help="files/dirs to lint (default: yjs_tpu/ scripts/)",
    )
    ap.add_argument(
        "--ci",
        action="store_true",
        help="gate mode: nonzero exit on any unsuppressed finding",
    )
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--verbose",
        "-v",
        action="store_true",
        help="also list suppressed + baselined findings",
    )
    ap.add_argument(
        "--baseline",
        default=str(DEFAULT_BASELINE),
        help="baseline file (default: .ytpu-lint-baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline (report grandfathered findings too)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="rewrite the baseline to cover every current finding",
    )
    ap.add_argument(
        "--list-rules",
        action="store_true",
        help="print every rule id with its severity and exit",
    )
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, sev in sorted(all_rules().items()):
            print(f"{rule:24s} {sev}")
        return 0

    baseline = (
        Baseline([])
        if args.no_baseline or args.write_baseline
        else Baseline.load(args.baseline)
    )
    targets = [Path(t) for t in args.targets] if args.targets else None
    result = run_lint(ROOT, targets=targets, baseline=baseline)

    if args.write_baseline:
        entries = [
            Baseline.entry_for(f, note="grandfathered by --write-baseline")
            for f in result.findings
            if f.rule
            not in ("useless-suppression", "bare-suppression")
        ]
        Baseline(entries).save(args.baseline)
        print(
            f"wrote {len(entries)} entr"
            f"{'y' if len(entries) == 1 else 'ies'} to {args.baseline}"
        )
        return 0

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.as_dict() for f in result.findings],
                    "suppressed": [
                        f.as_dict() for f in result.suppressed
                    ],
                    "baselined": [f.as_dict() for f in result.baselined],
                    "stale_baseline": result.stale_baseline,
                },
                indent=1,
            )
        )
    else:
        print(render_report(result, verbose=args.verbose))

    if result.failed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
