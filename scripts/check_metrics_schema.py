#!/usr/bin/env python
"""Diff the LIVE registered metric names against README's documented list.

Instantiates a provider (which registers every engine + provider metric
family at construction) plus the process-global registry, extracts the
``ytpu_*`` names from the README Observability table, and fails when
either side has a name the other lacks — so the docs and the exposition
surface cannot drift apart.  Also cross-checks the resilience/chaos/
durability/profiling/network/fleet env knobs (``YTPU_CHAOS_*`` /
``YTPU_RESILIENCE_*`` / ``YTPU_DLQ_*`` / ``YTPU_WAL_*`` /
``YTPU_PROF_*`` / ``YTPU_SLO_*`` / ``YTPU_NET_*`` / ``YTPU_FLEET_*`` /
``YTPU_TIER_*`` / ``YTPU_ADM_*``)
read by the code against the knobs README documents.  Wired as a tier-1
check via tests/test_obs.py-adjacent usage, scripts/ci_check.sh, and
runnable standalone:

    python scripts/check_metrics_schema.py
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def documented_names(readme_text: str) -> set[str]:
    """Backticked ytpu_* names from the Observability metric table rows
    (lines shaped ``| `ytpu_...` | kind | ...``)."""
    names = set()
    for line in readme_text.splitlines():
        m = re.match(r"\|\s*`(ytpu_[a-z0-9_]+)`\s*\|", line)
        if m:
            names.add(m.group(1))
    return names


def registered_names() -> set[str]:
    from yjs_tpu.fleet import FleetRouter
    from yjs_tpu.obs import global_registry
    from yjs_tpu.provider import TpuProvider

    prov = TpuProvider(1)
    # the smallest possible fleet registers every ytpu_fleet_* family
    # on the global registry (ISSUE 6)
    FleetRouter(1, 1)
    return set(prov.engine.obs.registry.names()) | set(
        global_registry().names()
    )


_KNOB_RE = re.compile(
    r"YTPU_(?:CHAOS|RESILIENCE|DLQ|WAL|PROF|SLO|NET|FLEET|TIER|REPL"
    r"|FAILOVER|PLAN|ADM|TRACE|BLACKBOX|FLUSH)_[A-Z0-9_]+"
)


def resilience_knobs_in_code() -> set[str]:
    """Resilience/chaos env names the package actually reads."""
    knobs: set[str] = set()
    for path in (ROOT / "yjs_tpu").rglob("*.py"):
        knobs |= set(_KNOB_RE.findall(path.read_text()))
    return knobs


def resilience_knobs_in_readme(readme_text: str) -> set[str]:
    return set(_KNOB_RE.findall(readme_text))


def main() -> int:
    readme = (ROOT / "README.md").read_text()
    doc = documented_names(readme)
    live = registered_names()
    if not live:
        print("obs disabled (YTPU_OBS_DISABLED) — nothing to check")
        return 0
    undocumented = sorted(live - doc)
    stale = sorted(doc - live)
    if undocumented:
        print("registered but NOT in README's Observability table:")
        for n in undocumented:
            print(f"  {n}")
    if stale:
        print("documented in README but NOT registered:")
        for n in stale:
            print(f"  {n}")
    code_knobs = resilience_knobs_in_code()
    doc_knobs = resilience_knobs_in_readme(readme)
    knob_undoc = sorted(code_knobs - doc_knobs)
    knob_stale = sorted(doc_knobs - code_knobs)
    if knob_undoc:
        print("env knobs read by the code but NOT in README:")
        for n in knob_undoc:
            print(f"  {n}")
    if knob_stale:
        print("env knobs in README but NOT read by the code:")
        for n in knob_stale:
            print(f"  {n}")
    if undocumented or stale or knob_undoc or knob_stale:
        return 1
    print(
        f"ok: {len(live)} metric families and {len(code_knobs)} "
        "resilience env knobs, docs and code agree"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
