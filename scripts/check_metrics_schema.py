#!/usr/bin/env python
"""Diff the LIVE registered metric names against README's documented list.

Instantiates a provider (which registers every engine + provider metric
family at construction) plus the process-global registry, extracts the
``ytpu_*`` names from the README Observability table, and fails when
either side has a name the other lacks — so the docs and the exposition
surface cannot drift apart.  Wired as a tier-1 check via
tests/test_obs.py-adjacent usage and runnable standalone:

    python scripts/check_metrics_schema.py
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))


def documented_names(readme_text: str) -> set[str]:
    """Backticked ytpu_* names from the Observability metric table rows
    (lines shaped ``| `ytpu_...` | kind | ...``)."""
    names = set()
    for line in readme_text.splitlines():
        m = re.match(r"\|\s*`(ytpu_[a-z0-9_]+)`\s*\|", line)
        if m:
            names.add(m.group(1))
    return names


def registered_names() -> set[str]:
    from yjs_tpu.obs import global_registry
    from yjs_tpu.provider import TpuProvider

    prov = TpuProvider(1)
    return set(prov.engine.obs.registry.names()) | set(
        global_registry().names()
    )


def main() -> int:
    doc = documented_names((ROOT / "README.md").read_text())
    live = registered_names()
    if not live:
        print("obs disabled (YTPU_OBS_DISABLED) — nothing to check")
        return 0
    undocumented = sorted(live - doc)
    stale = sorted(doc - live)
    if undocumented:
        print("registered but NOT in README's Observability table:")
        for n in undocumented:
            print(f"  {n}")
    if stale:
        print("documented in README but NOT registered:")
        for n in stale:
            print(f"  {n}")
    if undocumented or stale:
        return 1
    print(f"ok: {len(live)} metric families, docs and registry agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
