#!/usr/bin/env python
"""Diff the LIVE registered metric names against README's documented list.

Thin shim over :func:`yjs_tpu.analysis.drift.live_comparison` — the
knob/metric drift logic moved into the ytpu-lint static-analysis suite
(``scripts/ytpu_lint.py``, rules ``knob-drift`` / ``metric-drift``),
which additionally checks at the AST level that every ``YTPU_*`` env
read and literal ``ytpu_*`` registration is documented.  This script
keeps the original live half: instantiate a provider + the smallest
fleet, extract the registered family names, and fail when they and the
README Observability table disagree (plus the curated-prefix env-knob
cross-check).  Wired into scripts/ci_check.sh and runnable standalone:

    python scripts/check_metrics_schema.py
"""

from __future__ import annotations

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from yjs_tpu.analysis.drift import (  # noqa: E402
    KNOB_RE,
    documented_metrics,
    live_comparison,
)


# -- original module API, kept for the tier-1 tests that import it ------------

def documented_names(readme_text: str) -> set[str]:
    """Backticked ytpu_* names from the Observability metric table."""
    return documented_metrics(readme_text)


def registered_names() -> set[str]:
    from yjs_tpu.analysis.runner import register_lint_metric
    from yjs_tpu.fleet import FleetRouter
    from yjs_tpu.obs import global_registry
    from yjs_tpu.provider import TpuProvider

    prov = TpuProvider(1)
    # the smallest possible fleet registers every ytpu_fleet_* family
    # on the global registry (ISSUE 6); the lint counter is part of the
    # documented contract too
    FleetRouter(1, 1)
    register_lint_metric()
    # cluster families are lazily-registered process-global singletons —
    # touch each holder so the live set includes them
    from yjs_tpu.cluster.gateway import _GatewayMetricsSingleton
    from yjs_tpu.cluster.rpc import rpc_metrics
    from yjs_tpu.cluster.supervisor import _ClusterMetrics

    _GatewayMetricsSingleton.get()
    rpc_metrics()
    _ClusterMetrics()
    from yjs_tpu.obs.admin import admin_metrics
    from yjs_tpu.obs.federate import fed_metrics

    admin_metrics()
    fed_metrics()
    return set(prov.engine.obs.registry.names()) | set(
        global_registry().names()
    )


def resilience_knobs_in_code() -> set[str]:
    """Curated-prefix env names the package actually mentions."""
    knobs: set[str] = set()
    for path in (ROOT / "yjs_tpu").rglob("*.py"):
        knobs |= set(KNOB_RE.findall(path.read_text()))
    return knobs


def main() -> int:
    problems = live_comparison(ROOT)
    for p in problems:
        print(p)
    if problems:
        return 1
    print("ok: live metric families and env knobs agree with README")
    return 0


if __name__ == "__main__":
    sys.exit(main())
