"""Generate the corrupt-update fixture set for the resilience suite.

Takes small valid V1 updates built from B4-style editing traffic (same
texture as scripts/gen_b4_fixture.py, tiny scale) and damages them the
ways transports and disks actually do: single bit flips, truncations at
varint/struct boundaries, and varint overflows (continuation-bit runs
that inflate a length/count field past any plausible buffer).

Every corrupt payload is VERIFIED rejected by
``yjs_tpu.updates.validate_update`` before it is written — a corruption
that still decodes is a Byzantine input, out of scope for the quarantine
tests (see yjs_tpu/resilience/chaos.py's detectability contract).

Writes, under tests/fixtures/corrupt/:

- ``manifest.json`` — schema version, generator seed, and one record per
  case: file name, corruption kind, source update length, and notes;
- ``<case>.bin`` — the corrupt bytes;
- ``valid_base.bin`` — the clean source update the cases derive from
  (lets tests assert the uncorrupted twin still integrates).

Usage: python scripts/gen_corrupt_fixtures.py [seed]
"""

from __future__ import annotations

import json
import random
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import yjs_tpu as Y
from yjs_tpu.updates import InvalidUpdate, validate_update

SCHEMA_VERSION = 1
OUT_DIR = Path(__file__).resolve().parent.parent / "tests" / "fixtures" / "corrupt"


def base_update(seed: int) -> bytes:
    """A small multi-client V1 update with inserts AND deletes (so the
    DS section is non-empty and truncations can land inside it)."""
    gen = random.Random(seed)
    a = Y.Doc(gc=False)
    a.client_id = 11
    b = Y.Doc(gc=False)
    b.client_id = 22
    for k in range(40):
        d = a if gen.random() < 0.6 else b
        t = d.get_text("text")
        if t and gen.random() < 0.3:
            t.delete(gen.randrange(len(t)), 1)
        else:
            pos = gen.randrange(len(t) + 1)
            t.insert(pos, gen.choice("abcdefgh "))
        if k % 10 == 9:
            ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
            ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
            Y.apply_update(b, ua)
            Y.apply_update(a, ub)
    ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
    Y.apply_update(b, ua)
    return Y.encode_state_as_update(b)


def bit_flips(update: bytes, gen: random.Random, want: int = 6) -> list[tuple[bytes, str]]:
    """``want`` distinct single-bit flips, each verified invalid."""
    out = []
    tried = set()
    while len(out) < want and len(tried) < 8 * len(update):
        i = gen.randrange(len(update))
        bit = gen.randrange(8)
        if (i, bit) in tried:
            continue
        tried.add((i, bit))
        cand = bytearray(update)
        cand[i] ^= 1 << bit
        cand = bytes(cand)
        try:
            validate_update(cand)
        except InvalidUpdate:
            out.append((cand, f"bit {bit} of byte {i} flipped"))
    return out


def truncations(update: bytes, gen: random.Random, want: int = 6) -> list[tuple[bytes, str]]:
    cuts = {0, 1, len(update) // 2, len(update) - 1}
    while len(cuts) < want + 4:
        cuts.add(gen.randrange(len(update)))
    out = []
    for cut in sorted(cuts):
        cand = update[:cut]
        try:
            validate_update(cand)
        except InvalidUpdate:
            out.append((cand, f"cut to {cut} of {len(update)} bytes"))
        if len(out) >= want:
            break
    return out


def varint_overflows(update: bytes) -> list[tuple[bytes, str]]:
    """Inflate varints the decoder trusts for sizing/counting."""
    return [
        # leading client-count varint inflated to ~2**63: the struct
        # loop exhausts the buffer long before reading that many
        (b"\xff" * 9 + update, "client-count varint inflated (9 cont. bytes)"),
        # a varint that never terminates (every byte continues)
        (b"\xff" * len(update), "all-continuation-bytes varint, no terminator"),
        # plausible-looking count with no structs behind it
        (b"\x7f" + update[1:2], "count 127 then immediate end of buffer"),
    ]


def main(seed: int = 13) -> None:
    gen = random.Random(seed)
    update = base_update(seed)
    validate_update(update)  # the base MUST be clean

    OUT_DIR.mkdir(parents=True, exist_ok=True)
    (OUT_DIR / "valid_base.bin").write_bytes(update)

    cases = []
    kinds = (
        [("bitflip", c, note) for c, note in bit_flips(update, gen)]
        + [("truncation", c, note) for c, note in truncations(update, gen)]
        + [("varint_overflow", c, note) for c, note in varint_overflows(update)]
    )
    for n, (kind, payload, note) in enumerate(kinds):
        try:
            validate_update(payload)
        except InvalidUpdate as e:
            reason = f"{type(e).__name__}"
        else:
            raise SystemExit(
                f"case {kind}/{note} decodes as valid — Byzantine, refuse to write"
            )
        name = f"{kind}_{n:02d}.bin"
        (OUT_DIR / name).write_bytes(payload)
        cases.append({
            "file": name,
            "kind": kind,
            "bytes": len(payload),
            "source_bytes": len(update),
            "note": note,
            "rejected_as": reason,
        })

    manifest = {
        "schema": SCHEMA_VERSION,
        "seed": seed,
        "base": {"file": "valid_base.bin", "bytes": len(update)},
        "cases": cases,
    }
    (OUT_DIR / "manifest.json").write_text(json.dumps(manifest, indent=1))
    print(f"wrote {len(cases)} corrupt cases + base to {OUT_DIR}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 13)
