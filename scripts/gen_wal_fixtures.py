"""Generate the damaged-WAL fixture set for the durability suite.

Builds small real WAL directories by driving a :class:`TpuProvider`
with deterministic multi-room traffic (tiny segment size so rotation
actually happens), then damages copies of them the ways crashes and
disks actually do: torn tails on the final segment, single-bit flips in
sealed segments and checkpoint files, and mid-log truncations.  Each
case directory is a complete WAL a test can hand to
``TpuProvider.recover`` (on a tmp COPY — recovery truncates torn
tails in place).

The manifest records, per case, the GOLDEN recovery outcome computed at
generation time by actually recovering a scratch copy: the per-room
texts plus the key ``last_recovery`` stats.  A clean case is verified
byte-equal to the oracle texts before anything is written.

Writes, under tests/fixtures/wal/:

- ``manifest.json`` — schema version, generator seed, one record per
  case: directory, damage kind, notes, expected texts + recovery stats;
- ``<case>/`` — one WAL directory per case (segments + checkpoints).

Usage: python scripts/gen_wal_fixtures.py [seed]
"""

from __future__ import annotations

import json
import random
import shutil
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import yjs_tpu as Y
from yjs_tpu.persistence import WalConfig, list_checkpoints, list_segments
from yjs_tpu.provider import TpuProvider
from yjs_tpu.resilience import DiskFaultInjector

SCHEMA_VERSION = 1
OUT_DIR = Path(__file__).resolve().parent.parent / "tests" / "fixtures" / "wal"
ROOMS = ("alpha", "beta")


def room_updates(seed: int, n_ops: int = 50) -> list[bytes]:
    """Per-op incremental updates from three editing clients."""
    gen = random.Random(seed)
    docs, updates = [], []
    for k in range(3):
        d = Y.Doc(gc=False)
        d.client_id = 1000 * (seed + 1) + k
        d.on("update", lambda u, origin, doc: updates.append(bytes(u)))
        docs.append(d)
    for _ in range(n_ops):
        d = gen.choice(docs)
        t = d.get_text("text")
        if len(t) and gen.random() < 0.3:
            t.delete(gen.randrange(len(t)), 1)
        else:
            t.insert(gen.randrange(len(t) + 1), gen.choice("abcdef "))
    return updates


def build_wal(path: Path, seed: int, checkpoint_mid: bool) -> dict[str, str]:
    """Drive a provider into ``path``; returns the oracle texts."""
    prov = TpuProvider(
        len(ROOMS),
        backend="cpu",
        wal_dir=path,
        wal_config=WalConfig(segment_bytes=400, fsync="never"),
    )
    streams = {g: room_updates(seed + j) for j, g in enumerate(ROOMS)}
    half = {g: len(us) // 2 for g, us in streams.items()}
    for g, us in streams.items():
        for u in us[: half[g]]:
            prov.receive_update(g, u)
    if checkpoint_mid:
        prov.checkpoint()
    for g, us in streams.items():
        for u in us[half[g] :]:
            prov.receive_update(g, u)
    prov.flush()
    texts = {g: prov.text(g) for g in ROOMS}
    # a crashed predecessor never seals: leave the dir torn-write-ready
    prov.wal.abandon()
    return texts


def golden_recovery(case_dir: Path) -> dict:
    """Recover a scratch copy; return the observed texts + stats."""
    scratch = Path(tempfile.mkdtemp(prefix="walfix-"))
    shutil.rmtree(scratch)
    shutil.copytree(case_dir, scratch)
    try:
        prov = TpuProvider.recover(scratch, backend="cpu")
        lr = prov.last_recovery
        return {
            "texts": {g: prov.text(g) for g in sorted(prov._guids)},
            "outcome": lr["outcome"],
            "torn_truncations": lr["torn_truncations"],
            "corrupt_records": lr["corrupt_records"],
            "dead_lettered": lr["dead_lettered"],
        }
    finally:
        shutil.rmtree(scratch, ignore_errors=True)


def main(seed: int = 23) -> None:
    if OUT_DIR.exists():
        shutil.rmtree(OUT_DIR)
    OUT_DIR.mkdir(parents=True)

    cases = []

    def emit(name: str, kind: str, note: str, build_seed: int,
             checkpoint_mid: bool, damage=None) -> None:
        case_dir = OUT_DIR / name
        oracle = build_wal(case_dir, build_seed, checkpoint_mid)
        if damage is not None:
            note = f"{note}; {damage(case_dir)}"
        golden = golden_recovery(case_dir)
        if kind == "clean" and golden["texts"] != oracle:
            raise SystemExit(
                f"case {name}: clean recovery diverged from the oracle"
            )
        cases.append({
            "dir": name,
            "kind": kind,
            "note": note,
            "expected": golden,
        })

    inj = DiskFaultInjector(seed=seed)

    emit("clean", "clean", "undamaged log, no checkpoint", seed, False)
    emit("ckpt_clean", "clean", "undamaged log with a mid-stream "
         "checkpoint (snapshot-then-tail)", seed + 10, True)

    def tear_final(d: Path) -> str:
        _i, p = list_segments(d)[-1]
        cut = inj.tear(p, max_bytes=96)
        return f"tore {cut} bytes off {p.name}"

    emit("torn_tail_00", "torn_tail", "torn write on the final segment",
         seed + 20, False, tear_final)
    emit("torn_tail_01", "torn_tail", "torn write on the final segment, "
         "checkpointed history", seed + 30, True, tear_final)

    def flip_sealed(d: Path) -> str:
        _i, p = list_segments(d)[0]
        off = inj.bitflip(p, lo=8)
        return f"flipped a bit at offset {off} of {p.name}"

    emit("bitflip_00", "bitflip", "one bit flipped in a sealed segment",
         seed + 40, False, flip_sealed)

    def flip_ckpt(d: Path) -> str:
        _u, p = list_checkpoints(d)[-1]
        off = inj.bitflip(p, lo=8)
        return f"flipped a bit at offset {off} of {p.name}"

    emit("ckpt_snapcorrupt_00", "bitflip", "one bit flipped in the "
         "checkpoint file's snapshot records", seed + 50, True, flip_ckpt)

    def midtrunc(d: Path) -> str:
        _i, p = list_segments(d)[0]
        size = p.stat().st_size
        keep = max(9, size // 2)
        p.write_bytes(p.read_bytes()[:keep])
        return f"truncated {p.name} from {size} to {keep} bytes"

    emit("midtrunc_00", "midtrunc", "sealed segment cut in half "
         "(unparseable tail, resync on the next file)", seed + 60,
         False, midtrunc)

    # damage landed for every damaged case (deterministic given seed)
    damaged = [c for c in cases if c["kind"] != "clean"]
    if any(c["expected"]["outcome"] == "clean" for c in damaged):
        raise SystemExit("a damaged case recovered 'clean' — damage missed")

    manifest = {
        "schema": SCHEMA_VERSION,
        "seed": seed,
        "rooms": list(ROOMS),
        "cases": cases,
    }
    (OUT_DIR / "manifest.json").write_text(json.dumps(manifest, indent=1))
    print(f"wrote {len(cases)} WAL cases to {OUT_DIR}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 23)
