"""Generate the B4-scale editing-trace fixture for bench.py.

The real crdt-benchmarks B4 dataset (a prosemirror paper-editing session,
~182k single-char inserts and ~77k single-char deletes — statistics cited in
reference INTERNALS.md:128-130) is not retrievable in this image, so this
synthesizes a trace with the same op counts and the same editing texture:
single-character ops at a mostly-sequential cursor (typing runs,
backspace-style delete runs, occasional cursor jumps), from two clients that
sync periodically.

Writes tests/fixtures/b4_trace.bin (the merged V1 update) and
tests/fixtures/b4_trace.json (op counts + the converged text's length and
sha256 + state vector, used by bench.py's convergence check).

Usage: python scripts/gen_b4_fixture.py [n_inserts n_deletes]
"""

from __future__ import annotations

import hashlib
import json
import random
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import yjs_tpu as Y

ALPHABET = "abcdefghijklmnopqrstuvwxyz     eettaaoinshr"


def generate(n_inserts: int = 182_000, n_deletes: int = 77_000, seed: int = 13):
    gen = random.Random(seed)
    a = Y.Doc(gc=False)
    a.client_id = 101
    b = Y.Doc(gc=False)
    b.client_id = 202

    def sync():
        ua = Y.encode_state_as_update(a, Y.encode_state_vector(b))
        ub = Y.encode_state_as_update(b, Y.encode_state_vector(a))
        Y.apply_update(b, ua)
        Y.apply_update(a, ub)

    ins = dels = 0
    # per-doc cursor (kept local across ops: the B4 texture)
    cursors = {id(a): 0, id(b): 0}
    active, other = a, b
    since_sync = 0
    t0 = time.time()
    while ins < n_inserts or dels < n_deletes:
        # stay on one client for a whole editing run
        if gen.random() < 0.02:
            active, other = other, active
        d = active
        t = d.get_text("text")
        ln = len(t)
        cur = min(cursors[id(d)], ln)
        if gen.random() < 0.05:  # jump to a new edit site
            cur = gen.randint(0, ln)
        # choose run type by remaining budget
        want_insert = ins < n_inserts and (
            dels >= n_deletes or gen.random() < n_inserts / (n_inserts + n_deletes)
        )
        run = gen.randint(2, 18)
        if want_insert:
            for _ in range(run):
                if ins >= n_inserts:
                    break
                t.insert(cur, gen.choice(ALPHABET))
                cur += 1
                ins += 1
        else:
            for _ in range(run):
                if dels >= n_deletes or cur == 0:
                    break
                t.delete(cur - 1, 1)  # backspace
                cur -= 1
                dels += 1
        cursors[id(d)] = cur
        since_sync += run
        if since_sync >= 2000:
            sync()
            since_sync = 0
        if (ins + dels) % 20000 < run:
            print(f"  {ins} ins / {dels} del  ({time.time()-t0:.0f}s)", flush=True)
    sync()
    text_a = a.get_text("text").to_string()
    assert text_a == b.get_text("text").to_string()
    update = Y.encode_state_as_update(a)
    meta = {
        "n_inserts": ins,
        "n_deletes": dels,
        "text_len": len(text_a),
        "text_sha256": hashlib.sha256(text_a.encode()).hexdigest(),
        "state_vector": {
            str(c): v for c, v in Y.get_state_vector(a.store).items() if v > 0
        },
        "seed": seed,
    }
    return update, meta


def main():
    n_ins = int(sys.argv[1]) if len(sys.argv) > 1 else 182_000
    n_del = int(sys.argv[2]) if len(sys.argv) > 2 else 77_000
    update, meta = generate(n_ins, n_del)
    fixtures = Path(__file__).resolve().parent.parent / "tests" / "fixtures"
    (fixtures / "b4_trace.bin").write_bytes(update)
    (fixtures / "b4_trace.json").write_text(json.dumps(meta, indent=1))
    print(json.dumps({**meta, "update_bytes": len(update)}, indent=1))


if __name__ == "__main__":
    main()
