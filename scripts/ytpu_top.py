#!/usr/bin/env python
"""ytpu_top: live fleet dashboard over yjs_tpu provider metrics.

A `top`-style view of one or more providers: flush throughput, queued
work, convergence latency percentiles, SLO burn-rate verdicts, and the
resilience / durability counters that page an operator (DLQ depth,
quarantined rooms, WAL fsync debt).

Sources (one row per provider):

    python scripts/ytpu_top.py snapA.json snapB.json
        Poll metrics-snapshot JSON files (as written by
        ``provider.metrics_snapshot()`` — e.g. a sidecar dumping the
        snapshot to a wellknown path every second).  Files are re-read
        every ``--interval`` seconds; rates are derived from consecutive
        reads.

    python scripts/ytpu_top.py /path/to/snapshot-dir/
        Federated mode: the directory's ``*.json`` files are treated as
        per-shard snapshots (the file-based scrape mode a multi-process
        fleet writes) and merged via ``yjs_tpu.obs.federate`` — one
        leading ``FLEET`` aggregate row (counters summed, histograms
        merged) above the per-shard rows.

    python scripts/ytpu_top.py --url http://127.0.0.1:9464 [--url ...]
        Live scrape mode (ISSUE 16): poll each process's admin-plane
        ``/metrics.json`` over HTTP.  Several ``--url`` flags federate
        under a leading ``FLEET`` row; a dead endpoint renders as a
        stale blank row.

    python scripts/ytpu_top.py --demo
        Run two in-process providers exchanging sync traffic, one frame
        of fresh edits per poll — the zero-to-dashboard smoke test.

    python scripts/ytpu_top.py --cluster /path/to/snapshot-dir/
        Cluster mode (ISSUE 14): the directory is a supervisor snapshot
        drop (``Supervisor.dump_snapshots`` / YTPU_CLUSTER_SNAPSHOT_DIR)
        — ``shard-K.json`` metric snapshots federate as in directory
        mode, and ``cluster.json`` (the structured recovery report)
        renders as a supervision panel above them: per-shard process
        state, restart counts, replay outcomes, and the event tail.

    python scripts/ytpu_top.py --url ... --range ytpu_engine_pending_docs
        History mode (ISSUE 19): one-shot query of each endpoint's
        embedded-TSDB ``/query`` (``--last`` seconds, ``--agg``
        combiner; a supervisor URL answers the federated cross-shard
        series), rendered as min/max/last plus a sparkline.

Renders with curses on a tty, plain text otherwise (or with ``--plain``);
``--once`` prints a single frame and exits (scripting / CI).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

COLUMNS = (
    ("provider", 14),
    ("flushes", 8),
    ("docs/s", 8),
    ("pend", 6),
    ("conv p50", 9),
    ("conv p99", 9),
    ("slo", 8),
    ("burn", 7),
    ("dlq", 5),
    ("quar", 5),
    ("wal rec", 8),
    ("occup", 6),
    ("ovlp", 6),
    ("residue", 8),
    ("plnhit", 7),
    ("hot", 5),
    ("warm", 5),
    ("cold", 5),
    ("brownout", 9),
    ("trend", 10),
)

# sparkline glyphs, low to high (the "trend" column and --range mode)
_SPARK = "▁▂▃▄▅▆▇█"
# docs/s polls kept per provider row for the trend sparkline
_TREND_LEN = 10


def sparkline(values, width: int | None = None) -> str:
    """Render a value series as a fixed-width unicode sparkline
    (newest-last; empty/constant series render as a flat line)."""
    vals = [float(v) for v in values]
    if width is not None:
        vals = vals[-width:]
    if not vals:
        return "-"
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vals
    )

# per-shard fleet rows (rendered when a snapshot carries a "fleet"
# block — see FleetRouter.fleet_snapshot / metrics_snapshot)
FLEET_COLUMNS = (
    ("provider", 14),
    ("shard", 6),
    ("docs", 6),
    ("cap", 5),
    ("occup", 6),
    ("warm", 5),
    ("cold", 5),
    ("state", 8),
    ("role", 8),
    ("dlq", 5),
    ("sess", 5),
    ("migr", 5),
    ("in", 4),
    ("out", 4),
    ("repl", 5),
    ("rlag", 5),
)

# per-peer session rows (rendered as a second table when any provider
# snapshot carries a "sessions" list — see provider.sessions_snapshot)
SESSION_COLUMNS = (
    ("provider", 14),
    ("room", 10),
    ("peer", 10),
    ("state", 12),
    ("outbox", 7),
    ("rtx", 6),
    ("ack age", 8),
    ("resumes", 8),
    ("shed", 5),
)

# per-link geo rows (rendered when a snapshot carries a "geo" block —
# see GeoReplicator.snapshot, surfaced by provider.metrics_snapshot and
# the fleet/cluster statusz pages)
GEO_COLUMNS = (
    ("provider", 14),
    ("region", 8),
    ("link", 8),
    ("state", 12),
    ("health", 8),
    ("outbox", 7),
    ("dirty", 6),
    ("lag B", 7),
    ("lag s", 7),
    ("reconn", 7),
    ("resume", 7),
    ("resync", 7),
    ("dl", 4),
    ("ship B", 8),
    ("defer B", 8),
)

_STATE_NAMES = {0: "ok", 1: "warning", 2: "page"}

# brownout degradation levels, abbreviated to fit the column
_ADM_NAMES = {0: "normal", 1: "shed-bg", 2: "coalesce", 3: "rej-write"}


def _counter(snap: dict, name: str, labels_key: str = "") -> float:
    return float(snap.get("counters", {}).get(name, {}).get(labels_key, 0))


def _gauge(snap: dict, name: str, labels_key: str = "") -> float:
    return float(snap.get("gauges", {}).get(name, {}).get(labels_key, 0))


def _hist(snap: dict, name: str, labels_key: str = "") -> dict | None:
    return snap.get("histograms", {}).get(name, {}).get(labels_key)


def _counter_sum(snap: dict, name: str) -> float:
    return float(sum(snap.get("counters", {}).get(name, {}).values()))


def collect_row(
    name: str, snap: dict, prev: dict | None, interval: float
) -> dict:
    """One dashboard row from a provider snapshot.  ``prev`` is the
    previous poll's row (its ``totals``) so rates survive file sources
    that only expose monotonic counters."""
    flushes = _counter(snap, "ytpu_engine_flushes_total")
    docs_flushed = _counter(snap, "ytpu_engine_docs_flushed_total")
    docs_rate = 0.0
    if prev is not None and interval > 0:
        docs_rate = max(0.0, docs_flushed - prev["totals"]["docs_flushed"])
        docs_rate /= interval
    conv = _hist(snap, "ytpu_convergence_latency_seconds")
    # per-row docs/s history feeding the trend sparkline (carried
    # poll-to-poll through the prev row like the rate totals)
    history = list((prev or {}).get("history") or ())
    history = (history + [docs_rate])[-_TREND_LEN:]
    slo = snap.get("slo") or {}
    state = slo.get("state")
    if state is None:
        state = _STATE_NAMES.get(int(_gauge(snap, "ytpu_slo_state")), "?")
    burns = slo.get("burn_rates") or {}
    burn = max(burns.values()) if burns else 0.0
    return {
        "provider": name,
        "flushes": int(flushes),
        "docs/s": f"{docs_rate:.1f}",
        "pend": int(_gauge(snap, "ytpu_engine_pending_docs")),
        "conv p50": f"{conv['p50'] * 1e3:.1f}ms" if conv else "-",
        "conv p99": f"{conv['p99'] * 1e3:.1f}ms" if conv else "-",
        "slo": state,
        "burn": f"{burn:.1f}",
        "dlq": int(_gauge(snap, "ytpu_resilience_dead_letter_depth")),
        "quar": int(_gauge(snap, "ytpu_resilience_docs_quarantined")),
        "wal rec": int(_counter_sum(snap, "ytpu_wal_records_appended_total")),
        "occup": f"{_gauge(snap, 'ytpu_prof_slot_occupancy'):.2f}",
        # flush-pipeline overlap fraction (ISSUE 12): share of host pack
        # time hidden behind an in-flight device dispatch ("-" until the
        # pipeline has packed at least one overlapped stage)
        "ovlp": (
            f"{_ov['sum'] / _pk['sum']:.2f}"
            if (_pk := _hist(snap, "ytpu_engine_phase_seconds",
                             "phase=pack"))
            and (_ov := _hist(snap, "ytpu_flush_pack_overlap_seconds"))
            and _pk["sum"] > 0
            else "-"
        ),
        # planner residue fraction (ISSUE 16): share of planned structs
        # handed to the sequential YATA conflict fallback on the last
        # flush with planner work ("-" until the planner has run)
        "residue": (
            f"{_re:.2f}"
            if (_re := snap.get("gauges", {})
                .get("ytpu_plan_segment_residue_fraction", {})
                .get("")) is not None
            else "-"
        ),
        # plan-cache hit rate (process-global counters; "-" before the
        # first planned flush)
        "plnhit": (
            f"{_counter(snap, 'ytpu_plan_cache_hits_total') / _pl:.2f}"
            if (_pl := _counter(snap, "ytpu_plan_cache_hits_total")
                + _counter(snap, "ytpu_plan_cache_misses_total"))
            else "-"
        ),
        "hot": int(_gauge(snap, "ytpu_tier_docs", "tier=hot")),
        "warm": int(_gauge(snap, "ytpu_tier_docs", "tier=warm")),
        "cold": int(_gauge(snap, "ytpu_tier_docs", "tier=cold")),
        "brownout": (
            "off"
            if not (snap.get("admission") or {}).get("enabled")
            else _ADM_NAMES.get(
                int((snap.get("admission") or {}).get("level", 0)), "?"
            )
        ),
        "trend": sparkline(history, _TREND_LEN),
        "history": history,
        "sessions": [
            {
                "provider": name,
                "room": str(s.get("guid", "?")),
                "peer": str(s.get("peer", "?")),
                "state": str(s.get("state", "?")),
                "outbox": int(s.get("outbox_depth", 0)),
                "rtx": int(s.get("retransmits", 0)),
                "ack age": int(s.get("last_ack_age", 0)),
                "resumes": int(s.get("resumes", 0)),
                "shed": int(s.get("shed", 0)),
            }
            for s in (snap.get("sessions") or [])
        ],
        "fleet": [
            {
                "provider": name,
                "shard": int(sh.get("shard", -1)),
                "docs": int(sh.get("docs", 0)),
                "cap": int(sh.get("capacity", 0)),
                "occup": f"{float(sh.get('occupancy', 0)):.2f}",
                "warm": int(sh.get("warm", 0)),
                "cold": int(sh.get("cold", 0)),
                "state": str(sh.get("state", "?")),
                "role": str(sh.get("role", "?")),
                "dlq": int(sh.get("dlq", 0)),
                "sess": int(sh.get("sessions", 0)),
                "migr": int(sh.get("migrating", 0)),
                "in": int(sh.get("mig_in", 0)),
                "out": int(sh.get("mig_out", 0)),
                "repl": int(sh.get("repl_docs", 0)),
                "rlag": int(sh.get("repl_lag", 0)),
            }
            for sh in (snap.get("fleet") or {}).get("shards", [])
        ],
        "fleet_head": (
            {
                "epoch": int((snap.get("fleet") or {}).get("epoch", 0)),
                "docs": int((snap.get("fleet") or {}).get("docs", 0)),
                "capacity": int(
                    (snap.get("fleet") or {}).get("capacity", 0)
                ),
                "live": int(
                    (snap.get("fleet") or {}).get("live_shards", 0)
                ),
                "migrating": int(
                    (snap.get("fleet") or {}).get("migrations_active", 0)
                ),
            }
            if snap.get("fleet")
            else None
        ),
        "geo": [
            {
                "provider": name,
                "region": str((snap.get("geo") or {}).get("region", "?")),
                "link": str(ln.get("link", "?")),
                "state": str(ln.get("state", "?")),
                "health": str(ln.get("detector", "?")),
                "outbox": int(ln.get("outbox", 0)),
                "dirty": int(ln.get("dirty_docs", 0)),
                "lag B": int(ln.get("lag_bytes", 0)),
                "lag s": f"{float(ln.get('lag_seconds', 0)):.1f}",
                "reconn": int(ln.get("reconnects", 0)),
                "resume": int(ln.get("resumes", 0)),
                "resync": int(ln.get("full_resyncs", 0)),
                "dl": int(ln.get("dead_letters", 0)),
                "ship B": int(ln.get("shipped_bytes", 0)),
                "defer B": int(ln.get("deferred_bytes", 0)),
            }
            for ln in (snap.get("geo") or {}).get("links", [])
        ],
        "geo_head": (
            {
                "region": str(snap["geo"].get("region", "?")),
                "epoch": int(snap["geo"].get("epoch", 0)),
                "links": len(snap["geo"].get("links", [])),
            }
            if snap.get("geo")
            else None
        ),
        "totals": {"docs_flushed": docs_flushed},
    }


def render(rows: list[dict], interval: float) -> str:
    """One plain-text frame: header line, column bar, one line per
    provider, and a worst-verdict footer."""
    stamp = time.strftime("%H:%M:%S")
    out = [
        f"ytpu_top  {stamp}  providers={len(rows)}  "
        f"interval={interval:g}s"
    ]
    out.append("  ".join(f"{title:>{w}}" for title, w in COLUMNS))
    worst = "ok"
    for row in rows:
        out.append(
            "  ".join(f"{str(row[title]):>{w}}" for title, w in COLUMNS)
        )
        order = {"ok": 0, "warning": 1, "page": 2}
        if order.get(row["slo"], 0) > order.get(worst, 0):
            worst = row["slo"]
    fleet_rows = [s for row in rows for s in row.get("fleet", [])]
    if fleet_rows:
        heads = [
            r["fleet_head"] for r in rows if r.get("fleet_head")
        ]
        out.append("")
        if heads:
            h = heads[0]
            out.append(
                f"fleet: epoch={h['epoch']}  docs={h['docs']}/"
                f"{h['capacity']}  live_shards={h['live']}  "
                f"migrating={h['migrating']}"
            )
        out.append(
            "  ".join(f"{title:>{w}}" for title, w in FLEET_COLUMNS)
        )
        for s in fleet_rows:
            out.append(
                "  ".join(
                    f"{str(s[title]):>{w}}" for title, w in FLEET_COLUMNS
                )
            )
    geo_rows = [g for row in rows for g in row.get("geo", [])]
    if geo_rows:
        heads = [r["geo_head"] for r in rows if r.get("geo_head")]
        out.append("")
        if heads:
            out.append(
                "geo: " + "  ".join(
                    f"region={h['region']} epoch={h['epoch']} "
                    f"links={h['links']}"
                    for h in heads
                )
            )
        out.append(
            "  ".join(f"{title:>{w}}" for title, w in GEO_COLUMNS)
        )
        for g in geo_rows:
            out.append(
                "  ".join(
                    f"{str(g[title]):>{w}}" for title, w in GEO_COLUMNS
                )
            )
    sess_rows = [s for row in rows for s in row.get("sessions", [])]
    if sess_rows:
        out.append("")
        out.append(
            "  ".join(f"{title:>{w}}" for title, w in SESSION_COLUMNS)
        )
        for s in sess_rows:
            out.append(
                "  ".join(
                    f"{str(s[title]):>{w}}" for title, w in SESSION_COLUMNS
                )
            )
    out.append(f"fleet verdict: {worst}")
    return "\n".join(out) + "\n"


# -- sources -----------------------------------------------------------------


class FileSource:
    """Polls snapshot JSON files (one provider per file), re-parsing
    only files whose ``(mtime_ns, size)`` changed since the previous
    frame — ``--watch``-style loops against slow-moving sidecar dumps
    stop burning a core re-reading identical JSON (ISSUE 19)."""

    def __init__(self, paths: list[str]):
        self.paths = [Path(p) for p in paths]
        self._cache: dict = {}  # path -> ((mtime_ns, size), snapshot)

    def poll(self) -> list[tuple[str, dict]]:
        out = []
        for p in self.paths:
            stamp = None
            try:
                st = p.stat()
                stamp = (st.st_mtime_ns, st.st_size)
            except OSError:
                pass
            hit = self._cache.get(p)
            if hit is not None and stamp is not None and hit[0] == stamp:
                out.append((p.stem, hit[1]))
                continue
            try:
                with open(p) as f:
                    snap = json.load(f)
            except (OSError, ValueError):
                out.append((p.stem, {}))  # unreadable: render an empty row
                continue
            if stamp is not None:
                self._cache[p] = (stamp, snap)
            out.append((p.stem, snap))
        return out


class DirSource:
    """Federated file mode: every poll reads each ``*.json`` in the
    directory as one shard's snapshot and prepends a ``FLEET`` row
    merged across them (``ytpu_top <dir>``).  Unchanged files are
    served from an mtime/size cache (``read_snapshot_dir``'s, ISSUE
    19) so a watch over a large fleet dir skips the re-parse."""

    def __init__(self, path: str):
        self.path = str(path)
        self._cache: dict = {}

    def poll(self) -> list[tuple[str, dict]]:
        from yjs_tpu.obs.federate import (
            federate_snapshots,
            read_snapshot_dir,
        )

        sources = read_snapshot_dir(self.path, cache=self._cache)
        out = [("FLEET", federate_snapshots(sources))]
        for src in sources:
            out.append(
                (str(src.get("label", "?")), src.get("snapshot") or {})
            )
        return out


CLUSTER_COLUMNS = (
    ("shard", 6),
    ("state", 11),
    ("pid", 8),
    ("port", 6),
    ("restarts", 9),
    ("outcome", 10),
    ("replayed", 9),
)


def render_cluster(report: dict) -> str:
    """The supervision panel: one row per shard process plus the
    resolution totals and the last few restart/failover events."""
    if not report:
        return "cluster: no cluster.json yet\n"
    out = [
        f"cluster epoch {report.get('epoch', 0)}  "
        f"outcomes {report.get('outcomes', {})}  "
        f"resolution {report.get('resolution', {})}"
    ]
    out.append(
        "  ".join(f"{title:>{w}}" for title, w in CLUSTER_COLUMNS)
    )
    for row in report.get("shards", ()):
        vals = {
            "shard": row.get("shard", "?"),
            "state": row.get("state", "?"),
            "pid": row.get("pid", 0),
            "port": row.get("port", 0),
            "restarts": row.get("restarts", 0),
            "outcome": row.get("outcome", ""),
            "replayed": row.get("records_applied", 0),
        }
        out.append(
            "  ".join(
                f"{str(vals[title]):>{w}}" for title, w in CLUSTER_COLUMNS
            )
        )
    for ev in (report.get("events") or [])[-3:]:
        out.append(
            f"  event: shard {ev.get('shard')} {ev.get('outcome')} "
            f"epoch={ev.get('epoch')} "
            f"unavailable={ev.get('unavailable_s')}s "
            f"resolution={ev.get('resolution')}"
        )
    return "\n".join(out) + "\n"


class ClusterDirSource:
    """Supervisor snapshot-dir mode (``--cluster``): ``shard-*.json``
    federate like :class:`DirSource`, ``cluster.json`` feeds the
    supervision panel via :meth:`header`."""

    def __init__(self, path: str):
        self.path = str(path)
        self._cache: dict = {}

    def _report(self) -> dict:
        try:
            with open(Path(self.path) / "cluster.json") as f:
                return json.load(f)
        except (OSError, ValueError):
            return {}  # mid-write or not dumped yet: empty panel

    def header(self) -> str:
        return render_cluster(self._report())

    def poll(self) -> list[tuple[str, dict]]:
        from yjs_tpu.obs.federate import (
            federate_snapshots,
            read_snapshot_dir,
        )

        sources = [
            s for s in read_snapshot_dir(self.path, cache=self._cache)
            if str(s.get("label", "")) != "cluster"
        ]
        out = [("CLUSTER", federate_snapshots(sources))]
        for src in sources:
            out.append(
                (str(src.get("label", "?")), src.get("snapshot") or {})
            )
        return out


class UrlSource:
    """Admin-plane scrape mode (``--url``, ISSUE 16): every poll GETs
    each endpoint's ``/metrics.json`` via
    :func:`~yjs_tpu.obs.federate.scrape_endpoints`.  One URL renders a
    single provider row; several federate under a leading ``FLEET``
    row, with dead endpoints as stale blank rows (never a crash)."""

    def __init__(self, urls: list[str], timeout_s: float = 2.0):
        self.urls = list(urls)
        self.timeout_s = timeout_s

    def poll(self) -> list[tuple[str, dict]]:
        from yjs_tpu.obs.federate import (
            federate_snapshots,
            scrape_endpoints,
        )

        sources = scrape_endpoints(self.urls, timeout_s=self.timeout_s)
        out = []
        if len(sources) > 1:
            out.append(("FLEET", federate_snapshots(sources)))
        for src in sources:
            out.append(
                (str(src.get("label", "?")), src.get("snapshot") or {})
            )
        return out


class DemoSource:
    """Two in-process providers joined by per-room peer sessions over
    an in-memory pipe; every poll applies one fresh edit and pumps the
    wire, so the session table renders live states and ack ages."""

    def __init__(self):
        from yjs_tpu.provider import TpuProvider
        from yjs_tpu.sync import PipeNetwork

        self.a = TpuProvider(8)
        self.b = TpuProvider(8)
        self._n = 0
        self.net = PipeNetwork()
        for k in range(4):
            t1, t2 = self.net.pair()
            self.a.session(f"room{k}", "provider-b").connect(t1)
            self.b.session(f"room{k}", "provider-a").connect(t2)

    def _drive(self) -> None:
        self.a.flush()
        self.b.flush()
        self.a.tick_sessions()
        self.b.tick_sessions()

    def poll(self) -> list[tuple[str, dict]]:
        from yjs_tpu.core import Doc
        from yjs_tpu.updates import encode_state_as_update

        self._n += 1
        d = Doc(gc=False)
        d.get_text("text").insert(0, f"edit {self._n} ")
        u = encode_state_as_update(d)
        self.a.receive_update(f"room{self._n % 4}", u)
        self.net.settle((self._drive,))
        return [
            ("provider-a", self.a.metrics_snapshot()),
            ("provider-b", self.b.metrics_snapshot()),
        ]


# -- history range mode (ISSUE 19) -------------------------------------------


def run_range(
    urls: list[str], name: str, labels: str, last_s: float, agg: str,
    timeout_s: float = 2.0, out=None,
) -> int:
    """``--range``: one shot against each admin endpoint's embedded-TSDB
    ``/query`` (a supervisor URL answers with the federated cross-shard
    series), rendered as min/max/last plus a sparkline per endpoint."""
    from yjs_tpu.obs.tsdb import query_endpoints

    out = out or sys.stdout
    end = time.time()
    results = query_endpoints(
        {u: u for u in urls},
        {
            "name": name,
            "labels": labels,
            "start": end - last_s,
            "end": end,
            "agg": agg,
        },
        timeout_s=timeout_s,
    )
    out.write(
        f"ytpu_top --range  {name}"
        + (f"{{{labels}}}" if labels else "")
        + f"  last {last_s:g}s  agg={agg}\n"
    )
    rc = 1
    for url in sorted(results):
        res = results[url]
        pts = res.get("points") or []
        if res.get("stale") or not pts:
            out.write(f"{url:>40}  (no data)\n")
            continue
        rc = 0
        vals = [v for _, v in pts]
        out.write(
            f"{url:>40}  n={len(vals):<4d} "
            f"min={min(vals):<10.4g} max={max(vals):<10.4g} "
            f"last={vals[-1]:<10.4g} {sparkline(vals, 40)}\n"
        )
    out.flush()
    return rc


# -- drivers -----------------------------------------------------------------


def run_plain(source, interval: float, iterations: int | None = None,
              out=None) -> None:
    out = out or sys.stdout
    prev: dict[str, dict] = {}
    n = 0
    while iterations is None or n < iterations:
        if n:
            time.sleep(interval)
        rows = [
            collect_row(name, snap, prev.get(name), interval)
            for name, snap in source.poll()
        ]
        prev = {r["provider"]: r for r in rows}
        header = getattr(source, "header", None)
        if header is not None:
            out.write(header())
        out.write(render(rows, interval))
        out.flush()
        n += 1


def run_curses(source, interval: float) -> None:  # pragma: no cover - tty
    import curses

    def loop(scr):
        curses.curs_set(0)
        scr.nodelay(True)
        prev: dict[str, dict] = {}
        while True:
            rows = [
                collect_row(name, snap, prev.get(name), interval)
                for name, snap in source.poll()
            ]
            prev = {r["provider"]: r for r in rows}
            scr.erase()
            header = getattr(source, "header", None)
            frame = (header() if header is not None else "") + render(
                rows, interval
            )
            for y, line in enumerate(frame.splitlines()):
                try:
                    scr.addnstr(y, 0, line, curses.COLS - 1)
                except curses.error:
                    break  # terminal shrank below the frame
            scr.refresh()
            t_end = time.time() + interval
            while time.time() < t_end:
                if scr.getch() in (ord("q"), 27):
                    return
                time.sleep(0.05)

    curses.wrapper(loop)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytpu_top", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("snapshots", nargs="*",
                    help="provider metrics-snapshot JSON files to poll, "
                         "or ONE directory of per-shard snapshots to "
                         "federate")
    ap.add_argument("--demo", action="store_true",
                    help="dashboard over two in-process demo providers")
    ap.add_argument("--url", action="append", default=[],
                    metavar="URL",
                    help="scrape a live admin endpoint's /metrics.json "
                         "(repeatable; several URLs federate under a "
                         "FLEET row)")
    ap.add_argument("--scrape-timeout", type=float, default=2.0,
                    help="per-endpoint HTTP deadline for --url "
                         "(default 2s)")
    ap.add_argument("--range", metavar="SERIES",
                    help="history mode (ISSUE 19): query each --url "
                         "endpoint's embedded-TSDB /query for this "
                         "series and print min/max/last + a sparkline, "
                         "then exit")
    ap.add_argument("--labels", default="",
                    help="label filter for --range (k=v,k2=v2 form, "
                         "default: the unlabeled series)")
    ap.add_argument("--last", type=float, default=3600.0,
                    help="seconds of history for --range (default 3600)")
    ap.add_argument("--agg", default="avg",
                    choices=("avg", "min", "max", "last", "sum", "count"),
                    help="downsample/federation aggregator for --range "
                         "(default avg)")
    ap.add_argument("--cluster", action="store_true",
                    help="treat the directory argument as a supervisor "
                         "snapshot drop and render the cluster.json "
                         "supervision panel above the shard rows")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="poll interval in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one frame and exit")
    ap.add_argument("--plain", action="store_true",
                    help="plain text frames even on a tty")
    args = ap.parse_args(argv)

    if args.range:
        if not args.url:
            ap.error("--range needs at least one --url endpoint")
        return run_range(
            args.url, args.range, args.labels, args.last, args.agg,
            timeout_s=args.scrape_timeout,
        )
    if args.demo:
        source = DemoSource()
    elif args.url:
        if args.snapshots:
            ap.error("--url and file/dir sources are mutually exclusive")
        source = UrlSource(args.url, timeout_s=args.scrape_timeout)
    elif args.cluster:
        if len(args.snapshots) != 1 or not Path(args.snapshots[0]).is_dir():
            ap.error("--cluster requires ONE snapshot directory")
        source = ClusterDirSource(args.snapshots[0])
    elif len(args.snapshots) == 1 and Path(args.snapshots[0]).is_dir():
        source = DirSource(args.snapshots[0])
    elif args.snapshots:
        source = FileSource(args.snapshots)
    else:
        ap.error("either snapshot files or --demo is required")

    if args.once:
        run_plain(source, args.interval, iterations=1)
        return 0
    if args.plain or not sys.stdout.isatty():
        run_plain(source, args.interval)
        return 0
    run_curses(source, args.interval)
    return 0


if __name__ == "__main__":
    sys.exit(main())
