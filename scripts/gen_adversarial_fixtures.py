"""Pre-generate the adversarial bench fixtures (bench.py r5 shapes).

- storm_traces_{OPS}.bin.z: N_STORM four-client conflict-storm traces
  (rare syncs -> long concurrent runs colliding at shared positions:
  deep YATA conflict scans + heavy pre-splitting), same framing as
  distinct_traces.
- prepend_frag_{CHARS}.bin.z: ONE update of a maximally fragmented
  prepend-built text (reference y-text.tests.js:297-324 worst case —
  one item per character, nothing can merge).

Workload generation is untimed by design; these files keep the bench
run inside its budget.
"""

import io
import os
import struct
import sys
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.argv = [""]

from bench import gen_prepend_fragmented, gen_trace  # noqa: E402

FIX = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures",
)

N_STORM = int(os.environ.get("N_STORM", "256"))
OPS = int(os.environ.get("YTPU_BENCH_OPS", "1500"))
CHARS = int(os.environ.get("YTPU_BENCH_FRAG_CHARS", "100000"))

storm_path = os.path.join(FIX, f"storm_traces_{OPS}.bin.z")
if not os.path.exists(storm_path):
    buf = io.BytesIO()
    buf.write(struct.pack("<II", N_STORM, OPS))
    for i in range(N_STORM):
        u, _ = gen_trace(OPS, seed=5000 + i, n_clients=4, sync_p=0.08)
        buf.write(struct.pack("<I", len(u)) + u)
        if (i + 1) % 32 == 0:
            print(f"storm {i + 1}/{N_STORM}", flush=True)
    with open(storm_path + ".tmp", "wb") as f:
        f.write(zlib.compress(buf.getvalue(), 9))
    os.replace(storm_path + ".tmp", storm_path)
    print("wrote", storm_path)

frag_path = os.path.join(FIX, f"prepend_frag_{CHARS}.bin.z")
if not os.path.exists(frag_path):
    u, _ = gen_prepend_fragmented(CHARS)
    with open(frag_path + ".tmp", "wb") as f:
        f.write(zlib.compress(u, 9))
    os.replace(frag_path + ".tmp", frag_path)
    print("wrote", frag_path, f"({len(u)} bytes raw)")
