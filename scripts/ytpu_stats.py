#!/usr/bin/env python
"""ytpu_stats: human-readable view of yjs_tpu observability snapshots.

Modes:

    python scripts/ytpu_stats.py <snapshot.json>
        Pretty-print a metrics snapshot written by
        ``engine.metrics_snapshot()`` / ``provider.metrics_snapshot()``
        (e.g. bench.py's BENCH_obs_metrics.json artifact).

    python scripts/ytpu_stats.py --merge shard0.json shard1.json ...
    python scripts/ytpu_stats.py --merge /path/to/snapshot-dir/
        Federate several per-shard snapshots (``yjs_tpu.obs.federate``:
        counters sum, gauges keep per-shard series plus an aggregate,
        histograms merge) and render the fleet view.

    python scripts/ytpu_stats.py --url http://127.0.0.1:9464 [--url ...]
        Scrape a live process's admin-plane ``/metrics.json`` (ISSUE
        16) and render it; several ``--url`` flags federate first.

    python scripts/ytpu_stats.py --demo [--prom|--json]
        Exercise a tiny in-process provider (a few rooms, a sync
        handshake, one undo, a WAL append, one dead letter) and dump its
        metrics: the rendered view by default, raw Prometheus text with
        --prom, the JSON snapshot with --json.  The zero-to-metrics
        smoke test for the obs subsystem.

``--watch SECONDS`` re-reads and re-renders the snapshot file (or
re-runs the demo workload) at that interval until interrupted.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# rendered section -> metric-name prefixes it collects; names matching
# no group land in "other" (a new family renders without a code change)
GROUPS = (
    # "flush" must precede "engine": first prefix match wins and the
    # flush pipeline families (ISSUE 12) share the ytpu_flush_ stem
    ("flush", ("ytpu_flush_",)),
    ("engine", ("ytpu_engine_",)),
    ("native planner", ("ytpu_native_",)),
    ("planner", ("ytpu_plan_",)),
    ("provider", ("ytpu_provider_",)),
    ("sync", ("ytpu_sync_",)),
    ("network (sessions)", ("ytpu_net_",)),
    ("resilience", ("ytpu_resilience_", "ytpu_doc_", "ytpu_dead_letter",
                    "ytpu_dlq_", "ytpu_chaos_")),
    ("durability (WAL)", ("ytpu_wal_",)),
    ("cost attribution (prof)", ("ytpu_prof_",)),
    ("convergence SLO", ("ytpu_convergence_", "ytpu_slo_")),
    ("tiering", ("ytpu_tier_",)),
    ("replication", ("ytpu_repl_", "ytpu_failover_")),
    ("admission", ("ytpu_adm_",)),
    ("admin plane", ("ytpu_admin_",)),
    ("tracing", ("ytpu_trace_",)),
    ("blackbox", ("ytpu_blackbox_",)),
    ("federation", ("ytpu_fed_",)),
)


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _group_of(name: str) -> str:
    for title, prefixes in GROUPS:
        if name.startswith(prefixes):
            return title
    return "other"


def render_snapshot(snap: dict) -> str:
    """Per-subsystem sections, each mixing that subsystem's counters,
    gauges, and histogram summaries (one reading order per failure
    domain instead of one per metric kind)."""
    by_group: dict[str, list[tuple[str, str]]] = {}

    def add(name, labels_key, val):
        label = f"{name}{{{labels_key}}}" if labels_key else name
        by_group.setdefault(_group_of(name), []).append((label, val))

    for name in sorted(snap.get("counters", {})):
        for labels_key, v in sorted(snap["counters"][name].items()):
            add(name, labels_key, _fmt(v))
    for name in sorted(snap.get("gauges", {})):
        for labels_key, v in sorted(snap["gauges"][name].items()):
            add(name, labels_key, _fmt(v))
    for name in sorted(snap.get("histograms", {})):
        for labels_key, s in sorted(snap["histograms"][name].items()):
            add(
                name, labels_key,
                f"n={s['count']} p50={_fmt(s['p50'])} p95={_fmt(s['p95'])} "
                f"p99={_fmt(s['p99'])} max={_fmt(s['max'])}",
            )

    lines: list[str] = []

    def section(title, rows):
        if not rows:
            return
        lines.append(title)
        w = max(len(r[0]) for r in rows)
        for name, val in rows:
            lines.append(f"  {name:<{w}}  {val}")
        lines.append("")

    for title, _ in GROUPS:
        section(title, by_group.get(title, []))
    section("other", by_group.get("other", []))

    fed = snap.get("federation")
    if fed:
        roles = fed.get("roles") or {}
        section(
            "federation",
            [
                ("sources", _fmt(fed.get("sources", 0))),
                ("roles",
                 ", ".join(f"{k}={v or '-'}"
                           for k, v in sorted(roles.items())) or "-"),
            ],
        )
    slo = snap.get("slo")
    if slo:
        section(
            "slo verdict",
            [
                ("state", slo.get("state", "?")),
                ("target_ms", _fmt(slo.get("target_ms", 0))),
                ("burn short/long",
                 f"{_fmt(slo.get('burn_rates', {}).get('short', 0))} / "
                 f"{_fmt(slo.get('burn_rates', {}).get('long', 0))}"),
                ("completed", _fmt(slo.get("completed", 0))),
                ("pending", _fmt(slo.get("pending", 0))),
            ],
        )
    flush = snap.get("flush")
    if flush:
        section(
            f"last flush (1 of {snap.get('n_flushes_recorded', '?')} "
            f"recorded, {len(snap.get('flush_history', []))} in ring)",
            [(k, _fmt(flush[k])) for k in sorted(flush)],
        )
    return "\n".join(lines).rstrip() + "\n"


def demo_snapshot():
    """A tiny provider workload touching every instrumented seam:
    flushes, a sync handshake, an undo, WAL appends, and one damaged
    frame routed to the dead-letter queue — so the durability and
    resilience sections render non-empty."""
    import tempfile

    from yjs_tpu import Doc
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.updates import encode_state_as_update

    prov = TpuProvider(4, wal_dir=tempfile.mkdtemp(prefix="ytpu-stats-"))
    for k in range(3):
        d = Doc(gc=False)
        d.get_text("text").insert(0, f"room {k} says hello")
        prov.receive_update(f"room{k}", encode_state_as_update(d))
    prov.flush()
    prov.handle_sync_message("room0", prov.sync_step1("room0"))
    # a transport-damaged frame: counted + dead-lettered, room survives
    prov.handle_sync_message("room2", b"\x02\xff\xff\xff")
    prov.enable_undo("room1")
    d = Doc(gc=False)
    d.get_text("text").insert(0, "undo me. ")
    prov.receive_update("room1", encode_state_as_update(d), undoable=True)
    prov.flush()
    prov.undo("room1")
    # one peer session over an in-memory pipe so the network section
    # renders live counters (handshake + a delivered update)
    from yjs_tpu.sync import PipeNetwork

    peer = TpuProvider(1)
    net = PipeNetwork()
    t1, t2 = net.pair()
    prov.session("room0", "demo-peer").connect(t1)
    peer.session("room0", "demo-host").connect(t2)
    net.settle((prov.tick_sessions, peer.tick_sessions))
    return prov


def _watch(render_once, interval: float, iterations: int | None = None,
           out=None) -> None:
    """Re-render every ``interval`` seconds (forever when ``iterations``
    is None; bounded for tests).  Each frame is separated by a ruled
    timestamp line rather than a screen clear, so output pipes well."""
    out = out or sys.stdout
    n = 0
    while iterations is None or n < iterations:
        if n:
            time.sleep(interval)
        stamp = time.strftime("%H:%M:%S")
        out.write(f"--- {stamp} ---\n")
        out.write(render_once())
        out.flush()
        n += 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytpu_stats", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("snapshot", nargs="*",
                    help="metrics snapshot JSON file(s); with --merge, "
                         "several per-shard files or one directory")
    ap.add_argument("--merge", action="store_true",
                    help="federate several per-shard snapshot files (or "
                         "a directory of them) into one labeled view "
                         "before rendering")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny provider workload instead of reading a file")
    ap.add_argument("--url", action="append", default=[],
                    metavar="URL",
                    help="scrape a live admin endpoint's /metrics.json "
                         "instead of reading a file (repeatable; "
                         "several URLs federate)")
    ap.add_argument("--scrape-timeout", type=float, default=2.0,
                    help="per-endpoint HTTP deadline for --url "
                         "(default 2s)")
    ap.add_argument("--prom", action="store_true",
                    help="with --demo: print Prometheus text instead")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="with --demo: print the raw JSON snapshot instead")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=None,
                    help="re-render at this interval until interrupted")
    args = ap.parse_args(argv)

    if args.demo:
        if args.watch is not None:
            _watch(
                lambda: render_snapshot(demo_snapshot().metrics_snapshot()),
                args.watch,
            )
            return 0
        prov = demo_snapshot()
        if args.prom:
            sys.stdout.write(prov.metrics_text())
        elif args.as_json:
            json.dump(prov.metrics_snapshot(), sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_snapshot(prov.metrics_snapshot()))
        return 0
    if args.url:
        if args.snapshot:
            ap.error("--url and snapshot files are mutually exclusive")
        from yjs_tpu.obs.federate import (
            federate_snapshots,
            scrape_endpoints,
        )

        def render_url():
            sources = scrape_endpoints(
                args.url, timeout_s=args.scrape_timeout
            )
            if len(sources) == 1:
                return render_snapshot(sources[0]["snapshot"] or {})
            return render_snapshot(federate_snapshots(sources))

        if args.watch is not None:
            _watch(render_url, args.watch)
            return 0
        sys.stdout.write(render_url())
        return 0
    if not args.snapshot:
        ap.error("either a snapshot file, --url, or --demo is required")

    if args.merge:
        from yjs_tpu.obs.federate import (
            federate_snapshots,
            read_snapshot_dir,
        )

        def render_file():
            paths = args.snapshot
            if len(paths) == 1 and Path(paths[0]).is_dir():
                sources = read_snapshot_dir(paths[0])
            else:
                sources = []
                for p in paths:
                    try:
                        with open(p) as f:
                            snap = json.load(f)
                    except (OSError, ValueError):
                        snap = {}
                    if not isinstance(snap, dict):
                        snap = {}
                    sources.append({
                        "label": Path(p).stem,
                        "role": str(snap.get("role", "") or ""),
                        "snapshot": snap,
                    })
            return render_snapshot(federate_snapshots(sources))
    elif len(args.snapshot) > 1:
        ap.error("multiple snapshot files require --merge")
    else:

        def render_file():
            with open(args.snapshot[0]) as f:
                return render_snapshot(json.load(f))

    if args.watch is not None:
        _watch(render_file, args.watch)
        return 0
    sys.stdout.write(render_file())
    return 0


if __name__ == "__main__":
    sys.exit(main())
