#!/usr/bin/env python
"""ytpu_stats: human-readable view of yjs_tpu observability snapshots.

Two modes:

    python scripts/ytpu_stats.py <snapshot.json>
        Pretty-print a metrics snapshot written by
        ``engine.metrics_snapshot()`` / ``provider.metrics_snapshot()``
        (e.g. bench.py's BENCH_obs_metrics.json artifact).

    python scripts/ytpu_stats.py --demo [--prom|--json]
        Exercise a tiny in-process provider (a few rooms, a sync
        handshake, one undo) and dump its metrics: the rendered view by
        default, raw Prometheus text with --prom, the JSON snapshot with
        --json.  The zero-to-metrics smoke test for the obs subsystem.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _fmt(v) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_snapshot(snap: dict) -> str:
    lines: list[str] = []

    def section(title, rows):
        if not rows:
            return
        lines.append(title)
        w = max(len(r[0]) for r in rows)
        for name, val in rows:
            lines.append(f"  {name:<{w}}  {val}")
        lines.append("")

    def flatten(kind_map):
        rows = []
        for name in sorted(kind_map):
            for labels_key, val in sorted(kind_map[name].items()):
                label = f"{name}{{{labels_key}}}" if labels_key else name
                rows.append((label, val))
        return rows

    section(
        "counters",
        [(n, _fmt(v)) for n, v in flatten(snap.get("counters", {}))],
    )
    section(
        "gauges",
        [(n, _fmt(v)) for n, v in flatten(snap.get("gauges", {}))],
    )
    section(
        "histograms (count / p50 / p95 / p99 / max)",
        [
            (
                n,
                f"{s['count']} / {_fmt(s['p50'])} / {_fmt(s['p95'])} / "
                f"{_fmt(s['p99'])} / {_fmt(s['max'])}",
            )
            for n, s in flatten(snap.get("histograms", {}))
        ],
    )
    flush = snap.get("flush")
    if flush:
        section(
            f"last flush (1 of {snap.get('n_flushes_recorded', '?')} "
            f"recorded, {len(snap.get('flush_history', []))} in ring)",
            [(k, _fmt(flush[k])) for k in sorted(flush)],
        )
    return "\n".join(lines).rstrip() + "\n"


def demo_snapshot():
    """A tiny provider workload touching every instrumented seam."""
    from yjs_tpu import Doc
    from yjs_tpu.provider import TpuProvider
    from yjs_tpu.updates import encode_state_as_update

    prov = TpuProvider(4)
    for k in range(3):
        d = Doc(gc=False)
        d.get_text("text").insert(0, f"room {k} says hello")
        prov.receive_update(f"room{k}", encode_state_as_update(d))
    prov.flush()
    prov.handle_sync_message("room0", prov.sync_step1("room0"))
    prov.enable_undo("room1")
    d = Doc(gc=False)
    d.get_text("text").insert(0, "undo me. ")
    prov.receive_update("room1", encode_state_as_update(d), undoable=True)
    prov.flush()
    prov.undo("room1")
    return prov


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ytpu_stats", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("snapshot", nargs="?", help="metrics snapshot JSON file")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny provider workload instead of reading a file")
    ap.add_argument("--prom", action="store_true",
                    help="with --demo: print Prometheus text instead")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="with --demo: print the raw JSON snapshot instead")
    args = ap.parse_args(argv)

    if args.demo:
        prov = demo_snapshot()
        if args.prom:
            sys.stdout.write(prov.metrics_text())
        elif args.as_json:
            json.dump(prov.metrics_snapshot(), sys.stdout, indent=2)
            sys.stdout.write("\n")
        else:
            sys.stdout.write(render_snapshot(prov.metrics_snapshot()))
        return 0
    if not args.snapshot:
        ap.error("either a snapshot file or --demo is required")
    with open(args.snapshot) as f:
        snap = json.load(f)
    sys.stdout.write(render_snapshot(snap))
    return 0


if __name__ == "__main__":
    sys.exit(main())
