"""Pre-generate the distinct-doc benchmark traces (bench.py variant 2).

1024 distinct two-client editing traces (gen_trace seeds 1000..2023),
stored as one file: varuint-free simple framing [u32 len][bytes]*.  The
bench loads these instead of synthesizing traces at run time (workload
generation is explicitly untimed, but 1024 CPU-core editing sessions take
~10 minutes — far beyond the bench budget)."""

import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.argv = [""]

from bench import gen_trace  # noqa: E402

N = int(os.environ.get("N_TRACES", "1024"))
OPS = int(os.environ.get("YTPU_BENCH_OPS", "1500"))
out_path = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "fixtures", f"distinct_traces_{OPS}.bin",
)

import io
import zlib

buf = io.BytesIO()
buf.write(struct.pack("<II", N, OPS))
for i in range(N):
    u, _ = gen_trace(OPS, seed=1000 + i)
    buf.write(struct.pack("<I", len(u)) + u)
    if (i + 1) % 64 == 0:
        print(f"{i + 1}/{N}", flush=True)
with open(out_path + ".z.tmp", "wb") as f:
    f.write(zlib.compress(buf.getvalue(), 9))
os.replace(out_path + ".z.tmp", out_path + ".z")
print("wrote", out_path + ".z")
