#!/usr/bin/env python
"""Validate a yjs_tpu Perfetto/Chrome trace for causal completeness.

Loads one or more Chrome-trace JSON files (``{"traceEvents": [...]}`` as
written by ``Tracer.save`` / ``YTPU_TRACE_PATH`` / the engine's
``export_chrome_trace``) and fails when the causal structure is broken:

- **flow arrows resolve**: every flow-finish event (``ph="f"``) has a
  matching flow-start (``ph="s"``) with the same id under the same
  name, and vice versa — an arrow with only one end means a producer
  and consumer disagreed on the hash-derived flow id, or an event was
  lost to ring truncation;
- **no orphan spans**: a flow-start whose arrow never lands is a
  pipeline stage that swallowed the update;
- **sampled chains complete**: every trace id stamped on an ingress
  span (``ytpu.provider.receive_update``) also reaches visibility (a
  ``ytpu.convergence`` flow-finish carrying the same trace id) —
  origin → visible, across however many providers' tracers were merged
  into the file.

    python scripts/check_trace.py TRACE.json [...]
    python scripts/check_trace.py --selftest

``--selftest`` builds a 3-shard replicated in-process fleet with
``YTPU_TRACE_SAMPLE=1``, pushes edits through the full ingress →
admission → shard flush → replication fan-out pipeline, merges every
shard tracer into ONE trace, and validates it — the CI proof that a
sampled update at one peer stitches into a single resolvable trace.

Chaos runs that kill shards mid-flight legitimately strand arrows;
validate only traces from runs that were allowed to finish.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# span/instant names that mark a trace's ingress into the stack
INGRESS_NAMES = ("ytpu.provider.receive_update",)
# flow-finish names that mark a trace reaching visibility
TERMINAL_NAMES = ("ytpu.convergence",)


def load_events(path_or_obj) -> list[dict]:
    if isinstance(path_or_obj, (list, dict)):
        obj = path_or_obj
    else:
        with open(path_or_obj) as f:
            obj = json.load(f)
    if isinstance(obj, dict):
        obj = obj.get("traceEvents", [])
    if not isinstance(obj, list):
        raise ValueError("not a Chrome trace (no traceEvents list)")
    return [e for e in obj if isinstance(e, dict)]


def validate_events(events: list[dict]) -> list[str]:
    """All violated invariants, as human-readable strings (empty =
    valid)."""
    errors: list[str] = []
    if not any(e.get("ph") not in ("M",) for e in events):
        return ["trace has no events"]

    # -- flow arrows resolve both ways, per name --------------------------
    starts: dict[str, set] = defaultdict(set)
    ends: dict[str, set] = defaultdict(set)
    for e in events:
        ph = e.get("ph")
        if ph not in ("s", "f"):
            continue
        name = str(e.get("name", "?"))
        if "id" not in e:
            errors.append(f"flow event {name!r} ph={ph} has no id")
            continue
        (starts if ph == "s" else ends)[name].add(e["id"])
    for name in sorted(set(starts) | set(ends)):
        dangling = sorted(starts[name] - ends[name])[:5]
        unsourced = sorted(ends[name] - starts[name])[:5]
        if dangling:
            errors.append(
                f"{len(starts[name] - ends[name])} flow arrow(s) for "
                f"{name!r} never land (orphan spans), e.g. ids "
                f"{dangling}"
            )
        if unsourced:
            errors.append(
                f"{len(ends[name] - starts[name])} flow arrow(s) for "
                f"{name!r} have no origin, e.g. ids {unsourced}"
            )

    # -- sampled chains complete: ingress trace id -> visible -------------
    ingress_traces: set[str] = set()
    terminal_traces: set[str] = set()
    for e in events:
        t = (e.get("args") or {}).get("trace")
        if not t:
            continue
        name = str(e.get("name", ""))
        if name.startswith(INGRESS_NAMES):
            ingress_traces.add(t)
        if name.startswith(TERMINAL_NAMES) and e.get("ph") == "f":
            terminal_traces.add(t)
    incomplete = sorted(ingress_traces - terminal_traces)
    if incomplete:
        errors.append(
            f"{len(incomplete)} sampled trace(s) never reached "
            f"visibility, e.g. {incomplete[:3]}"
        )
    return errors


def check_file(path: str) -> list[str]:
    try:
        events = load_events(path)
    except (OSError, ValueError) as e:
        return [f"unreadable: {e}"]
    return validate_events(events)


# -- selftest -----------------------------------------------------------------


def selftest() -> int:
    """3-shard replicated fleet, everything sampled, every shard tracer
    merged into one trace — must validate clean AND contain at least
    one complete cross-stage chain."""
    import os

    os.environ["YTPU_TRACE_SAMPLE"] = "1"
    try:
        from yjs_tpu.core import Doc
        from yjs_tpu.fleet import FleetRouter
        from yjs_tpu.updates import encode_state_as_update

        fleet = FleetRouter(3, 4, backend="cpu")
        docs = {}
        for k in range(4):
            d = Doc(gc=False)
            d.client_id = 100 + k
            docs[f"room-{k}"] = d
        for i in range(3):
            for g, d in sorted(docs.items()):
                d.get_text("text").insert(0, f"{g} edit {i} ")
                fleet.receive_update(g, encode_state_as_update(d))
            fleet.flush()
            fleet.tick()
        fleet.repl.repair_all()
        fleet.flush()

        events: list[dict] = []
        for p in fleet.shards:
            events.extend(p.engine.obs.tracer.trace_events())
        events.sort(key=lambda e: e.get("ts", 0.0))
    finally:
        del os.environ["YTPU_TRACE_SAMPLE"]

    errors = validate_events(events)
    ingress = {
        (e.get("args") or {}).get("trace")
        for e in events
        if str(e.get("name", "")).startswith(INGRESS_NAMES)
        and (e.get("args") or {}).get("trace")
    }
    repl_arrows = sum(
        1 for e in events
        if e.get("name") == "ytpu.repl.fanout" and e.get("ph") == "f"
    )
    if not ingress:
        errors.append("selftest produced no sampled ingress spans")
    if not repl_arrows:
        errors.append("selftest produced no replication fan-out arrows")
    if errors:
        print("selftest FAILED:")
        for msg in errors:
            print(f"  {msg}")
        return 1
    print(
        f"selftest ok: {len(events)} events, {len(ingress)} sampled "
        f"traces origin->visible, {repl_arrows} replication arrows "
        "resolved across 3 shards"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="check_trace", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("traces", nargs="*", help="Chrome-trace JSON files")
    ap.add_argument("--selftest", action="store_true",
                    help="build a replicated in-process fleet and "
                         "validate its merged trace")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if not args.traces:
        ap.error("either trace files or --selftest is required")
    rc = 0
    for path in args.traces:
        errors = check_file(path)
        if errors:
            rc = 1
            print(f"{path}: INVALID")
            for msg in errors:
                print(f"  {msg}")
        else:
            print(f"{path}: ok")
    return rc


if __name__ == "__main__":
    sys.exit(main())
