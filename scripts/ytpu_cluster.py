#!/usr/bin/env python
"""Launch a process-native y-tpu cluster from the CLI (ISSUE 14).

Spawns N shard processes under a :class:`~yjs_tpu.cluster.Supervisor`
and fronts them with the y-websocket-compatible
:class:`~yjs_tpu.cluster.Gateway`, then runs until SIGINT/SIGTERM —
the operator-facing equivalent of the acceptance suite's topology.

Shape of a run::

    python scripts/ytpu_cluster.py --shards 3 --gateway 8765
    python scripts/ytpu_cluster.py --config cluster.json
    python scripts/ytpu_cluster.py --shards 1 --smoke   # CI round-trip

``--config`` takes a **docker-compose-shaped** JSON file, so the same
topology description moves between this launcher and a real compose
deployment without translation::

    {
      "services": {
        "shard": {
          "deploy": {"replicas": 3},
          "environment": {"YTPU_CLUSTER_HEARTBEAT_S": "0.25"}
        },
        "gateway": {
          "ports": ["8765:8765"],
          "environment": {"YTPU_GATEWAY_TICK_S": "0.05"}
        }
      }
    }

``services.shard.deploy.replicas`` is the shard count,
``services.gateway.ports[0]`` ("HOST:CONTAINER" or a bare port) is the
gateway port, and each service's ``environment`` map is applied to
``os.environ`` before the ``YTPU_CLUSTER_*`` / ``YTPU_GATEWAY_*``
configs are constructed (shard children inherit it).  CLI flags win
over the config file.

``--smoke`` connects one raw-session client through the gateway, makes
an edit, waits for the acked round-trip, verifies the text server-side,
then curls every spawned process's admin plane (``/healthz``,
``/statusz``, and a well-formed ``/metrics`` exposition on the
supervisor, each shard child, and the gateway — ISSUE 16), and exits
0/1 — the one-shot health probe `scripts/ci_check.sh` runs.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_compose(cfg: dict) -> dict:
    """Flatten a docker-compose-shaped dict into launcher settings:
    ``{"shards": int | None, "gateway_port": int | None, "env": dict}``.
    Unknown services/keys are ignored (the file may drive a real
    compose deployment with more in it)."""
    out = {"shards": None, "gateway_port": None, "env": {}}
    services = cfg.get("services") or {}
    shard = services.get("shard") or {}
    deploy = shard.get("deploy") or {}
    if "replicas" in deploy:
        out["shards"] = int(deploy["replicas"])
    gateway = services.get("gateway") or {}
    ports = gateway.get("ports") or []
    if ports:
        # compose publishes "HOST:CONTAINER"; the host side is ours
        host_port = str(ports[0]).split(":", 1)[0]
        out["gateway_port"] = int(host_port)
    for svc in (shard, gateway):
        env = svc.get("environment") or {}
        if isinstance(env, list):  # compose's KEY=VALUE list form
            env = dict(e.split("=", 1) for e in env if "=" in e)
        out["env"].update({str(k): str(v) for k, v in env.items()})
    return out


import re

# a Prometheus exposition sample line: name{labels} value [timestamp]
_EXPO_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})?"
    r" [-+]?([0-9.eE+-]+|NaN|Inf)( [0-9]+)?$"
)


def _check_admin(name: str, base: str) -> list[str]:
    """Curl one process's admin plane: /healthz, /statusz, and a
    well-formed /metrics exposition.  Returns failure strings."""
    import urllib.request

    fails = []
    for ep in ("/healthz", "/statusz", "/metrics"):
        try:
            with urllib.request.urlopen(base + ep, timeout=10) as r:
                body = r.read().decode("utf-8", "replace")
                if r.status != 200:
                    fails.append(f"{name}{ep}: HTTP {r.status}")
                    continue
        except OSError as e:
            fails.append(f"{name}{ep}: {e}")
            continue
        if ep == "/statusz":
            try:
                json.loads(body)
            except ValueError:
                fails.append(f"{name}{ep}: malformed JSON")
        elif ep == "/metrics":
            bad = [
                ln for ln in body.splitlines()
                if ln and not ln.startswith("#")
                and not _EXPO_LINE.match(ln)
            ]
            if bad:
                fails.append(
                    f"{name}{ep}: malformed exposition: {bad[0]!r}"
                )
            if "ytpu_" not in body:
                fails.append(f"{name}{ep}: no ytpu_ families")
    return fails


def _smoke_admin(gw, sup) -> list[str]:
    """Hit every spawned process's admin endpoints (ISSUE 16): the
    supervisor, each shard child, and the gateway."""
    fails = []
    urls = dict(sup.admin_urls())
    if "supervisor" not in urls:
        fails.append("supervisor: admin plane not serving")
    want_shards = {f"shard-{r['shard']:03d}"
                   for r in sup.recovery_report()["shards"]}
    missing = want_shards - set(urls)
    fails.extend(f"{m}: admin plane not serving" for m in sorted(missing))
    if gw.admin is not None and gw.admin.port:
        urls["gateway"] = gw.admin.url
    else:
        fails.append("gateway: admin plane not serving")
    for name, base in sorted(urls.items()):
        fails.extend(_check_admin(name, base))
    return fails


def _smoke(gw, sup) -> int:
    """One edit through the gateway's session dialect, verified
    server-side, plus an admin-plane probe of every process — exits
    nonzero unless both land."""
    import socket as socketlib

    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "examples",
        ),
    )
    from socket_connector import SocketConnector

    import yjs_tpu as Y

    room, text = "smoke-room", "cluster smoke ok"
    doc = Y.Doc()
    sock = socketlib.create_connection(("127.0.0.1", gw.port), timeout=30)
    conn = SocketConnector(doc, sock, room=room, peer="smoke-client")
    try:
        conn.connect()
        with conn.lock:
            doc.get_text("text").insert(0, text)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if sup.text(room) == text:
                    break
            except Exception:
                pass
            time.sleep(0.1)
        else:
            print("smoke: FAILED (edit never landed)", file=sys.stderr)
            return 1
        with conn.lock:
            snap = conn.session.snapshot()
        if snap.get("outbox_depth"):
            time.sleep(0.5)  # let the ack drain before judging
            with conn.lock:
                snap = conn.session.snapshot()
        admin_fails = _smoke_admin(gw, sup)
        if admin_fails:
            for f in admin_fails:
                print(f"smoke: admin FAILED {f}", file=sys.stderr)
            return 1
        print(
            "smoke: OK room=%r text=%r outbox=%s admin=ok"
            % (room, text, snap.get("outbox_depth"))
        )
        return 0
    finally:
        conn.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--shards", type=int, default=None,
                    help="shard process count (default 3)")
    ap.add_argument("--gateway", type=int, default=None, metavar="PORT",
                    help="gateway TCP port (default 0 = ephemeral)")
    ap.add_argument("--config", default=None, metavar="FILE",
                    help="docker-compose-shaped JSON topology file")
    ap.add_argument("--wal-root", default=None, metavar="DIR",
                    help="per-shard WAL root (default: a temp dir)")
    ap.add_argument("--snapshot-dir", default=None, metavar="DIR",
                    help="federated snapshot dir for ytpu_top --cluster")
    ap.add_argument("--docs-per-shard", type=int, default=64)
    ap.add_argument("--smoke", action="store_true",
                    help="one edit round-trip through the gateway, "
                         "then exit 0/1")
    args = ap.parse_args(argv)

    shards, gw_port = args.shards, args.gateway
    if args.config:
        with open(args.config) as f:
            compose = parse_compose(json.load(f))
        os.environ.update(compose["env"])
        if shards is None:
            shards = compose["shards"]
        if gw_port is None:
            gw_port = compose["gateway_port"]
    shards = 3 if shards is None else shards
    if shards < 1:
        ap.error("--shards must be >= 1")

    # env must be settled before the configs read it
    from yjs_tpu.cluster import (
        ClusterConfig, Gateway, GatewayConfig, Supervisor,
    )

    wal_root = args.wal_root or tempfile.mkdtemp(prefix="ytpu-cluster-")
    cconfig = ClusterConfig(
        snapshot_dir=args.snapshot_dir
        if args.snapshot_dir is not None else None,
    )
    gconfig = GatewayConfig(port=gw_port)

    sup = Supervisor(
        shards, wal_root, docs_per_shard=args.docs_per_shard, config=cconfig
    ).start()
    gw = Gateway(sup, config=gconfig).start()
    print(
        "ytpu-cluster: %d shard(s) up, gateway on %s:%d, wal-root %s"
        % (shards, gw.config.host, gw.port, wal_root)
    )
    for row in sup.recovery_report()["shards"]:
        print(
            "  shard %(shard)d: %(state)s pid=%(pid)s port=%(port)s" % row
        )

    if args.smoke:
        try:
            return _smoke(gw, sup)
        finally:
            gw.close()
            sup.close()

    stop = []
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.append(1))
    try:
        while not stop:
            time.sleep(0.25)
    finally:
        print("ytpu-cluster: shutting down")
        gw.close()
        sup.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
